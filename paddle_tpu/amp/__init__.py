"""AMP (paddle.amp parity: `python/paddle/amp/` — auto_cast O1/O2 with per-op
allow/block lists, GradScaler, decorate).

TPU-first: bf16 is the native mixed precision — no loss scaling needed, so
GradScaler defaults to a correct no-op pass-through when scaling is disabled
(paddle semantics kept: enable=True + fp16 scales, bf16 doesn't).
The O1 mechanism hooks the op-dispatch gate (`core.dispatch.set_amp_cast_hook`),
the TPU analog of the generated AmpAutoCast branches in eager forwards
(`paddle/fluid/eager/amp_utils.h`).
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch
from ..core import dtypes as _dtypes
from ..core.tensor import Tensor
from .grad_scaler import GradScaler, OptimizerState  # noqa: F401

# Per-op lists (subset of python/paddle/amp/amp_lists.py)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mv", "einsum",
    "addmm", "scaled_dot_product_attention",
}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "c_softmax_with_cross_entropy", "cross_entropy", "layer_norm", "rms_norm",
    "group_norm", "instance_norm", "batch_norm", "l1_loss", "mse_loss",
    "logsumexp", "erfinv", "pow", "cumsum", "prod", "std", "var", "norm",
}


class _AmpState:
    enabled = False
    level = "O1"
    dtype = jnp.bfloat16
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def _cast_leaf(x, dtype):
    if isinstance(x, Tensor) and jnp.issubdtype(x._value.dtype, np.floating) \
            and x._value.dtype != jnp.dtype(dtype):
        return x.astype(dtype)
    return x


def _amp_hook(op_name, args, kwargs):
    if not _state.enabled:
        return args, kwargs
    import jax

    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    if op_name in white:
        dt = _state.dtype
    elif op_name in black:
        dt = jnp.float32
    else:
        return args, kwargs

    def cast(x):
        return _cast_leaf(x, dt)

    args = jax.tree_util.tree_map(
        cast, args, is_leaf=lambda x: isinstance(x, Tensor))
    kwargs = jax.tree_util.tree_map(
        cast, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
    return args, kwargs


_dispatch.set_amp_cast_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    old = (_state.enabled, _state.level, _state.dtype, _state.custom_white,
           _state.custom_black)
    _state.enabled = enable
    _state.level = level
    _state.dtype = _dtypes.convert_dtype(dtype)
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.custom_white,
         _state.custom_black) = old


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False):
    """O2: cast model params to the amp dtype (master fp32 weights live in the
    optimizer's multi_precision machinery)."""
    dtype = _dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
        if optimizers is not None:
            opts = [optimizers] if not isinstance(optimizers, (list, tuple)) \
                else optimizers
            for o in opts:
                o._multi_precision = True if master_weight is None \
                    else master_weight
    if optimizers is None:
        return models
    return models, optimizers


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class debugging:
    """paddle.amp.debugging parity subset."""

    @staticmethod
    def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
        import jax.numpy as jnp

        v = tensor._value if isinstance(tensor, Tensor) else tensor
        n_nan = int(jnp.sum(jnp.isnan(v)))
        n_inf = int(jnp.sum(jnp.isinf(v)))
        if n_nan or n_inf:
            raise FloatingPointError(
                f"check_numerics failed for {op_type}:{var_name}: "
                f"{n_nan} NaN, {n_inf} Inf")
        return True

    _stats = None

    @classmethod
    def enable_operator_stats_collection(cls):
        """Collect per-op output dtype counts (parity:
        `paddle.amp.debugging.enable_operator_stats_collection` — used to
        audit which ops ran in bf16/fp32 under autocast)."""
        from ..core import dispatch

        cls._stats = {}
        dispatch.set_op_stats_sink(cls._stats)

    @classmethod
    def disable_operator_stats_collection(cls):
        from ..core import dispatch

        dispatch.set_op_stats_sink(None)
        stats = cls._stats or {}
        by_op = {}
        for (name, dtype), cnt in sorted(stats.items()):
            by_op.setdefault(name, {})[dtype] = cnt
        if by_op:
            print("<------------------- op list ------------------->")
            for name, dts in by_op.items():
                print(f"  {name}: " + ", ".join(
                    f"{d}={c}" for d, c in dts.items()))
        return by_op

    @classmethod
    def collect_operator_stats(cls):
        import contextlib

        @contextlib.contextmanager
        def g():
            cls.enable_operator_stats_collection()
            try:
                yield
            finally:
                cls.disable_operator_stats_collection()

        return g()

    @staticmethod
    def enable_tensor_checker():
        from ..core import flags

        flags.set_flags({"FLAGS_check_nan_inf": True})

    @staticmethod
    def disable_tensor_checker():
        from ..core import flags

        flags.set_flags({"FLAGS_check_nan_inf": False})
