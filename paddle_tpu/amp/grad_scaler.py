"""GradScaler (paddle.amp.grad_scaler parity:
`python/paddle/amp/grad_scaler.py:41,578` — dynamic loss scaling; the
check_finite_and_unscale/update_loss_scaling kernel pair from
`paddle/phi/kernels/amp_kernel.h` is fused here into jnp updates)."""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class OptimizerState(enum.Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._opt_state = OptimizerState.INIT
        self._guard = None

    def attach_guard(self, guard):
        """Compose with a `resilience.StepGuard`: every `update()`
        reports this step's overflow verdict.  Overflows while dynamic
        scaling still has room to shrink the scale are EXPECTED (source
        "amp": recorded as skips, no escalation); an overflow with the
        scale already at its floor is a genuinely sick step (source
        "amp_floor") and counts toward the warn→skip→rollback ladder."""
        self._guard = guard
        return self

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._params:
            if p.grad is not None:
                g = p.grad._value * inv
                bad = bool(jnp.any(~jnp.isfinite(g)))
                found = found or bad
                p.grad = Tensor(g)
        self._found_inf = found
        self._opt_state = OptimizerState.UNSCALED

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._opt_state != OptimizerState.UNSCALED:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._opt_state = OptimizerState.STEPPED

    def update(self):
        if not self._enable:
            return
        if self._guard is not None:
            # before the static-scaling early return: overflows must
            # reach the guard either way.  Static scaling (and a
            # dynamic scale already at its floor) has no room to shrink
            # out of the overflow, so those count toward the ladder.
            if self._found_inf:
                at_floor = (not self._dynamic) or self._scale <= 1.0
                self._guard.observe(
                    False, source="amp_floor" if at_floor else "amp")
            else:
                self._guard.observe(True, source="amp")
        if not self._dynamic:
            self._opt_state = OptimizerState.INIT
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._opt_state = OptimizerState.INIT

    def minimize(self, optimizer, loss):
        scaled = self.scale(loss)
        scaled.backward()
        self.step(optimizer)
        self.update()

    def get_loss_scaling(self):
        return Tensor(np.float32(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good_steps = d["good_steps"]
        self._bad_steps = d["bad_steps"]
