"""paddle.linalg namespace parity (`/root/reference/python/paddle/linalg.py`):
re-exports the decomposition/solve/factorisation ops from the op layer."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond_number as cond, corrcoef, cov, det, eig,
    eigh, eigvals, eigvalsh, householder_product, inverse as inv, lstsq, lu,
    lu_unpack, matrix_exp, matrix_power, matrix_rank, multi_dot, norm,
    ormqr, pca_lowrank, pinv, qr, slogdet, solve, svd, svdvals,
    triangular_solve, vecdot, vector_norm, matrix_norm,
)

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inv", "lstsq",
    "lu", "lu_unpack", "matrix_exp", "matrix_power", "matrix_rank",
    "multi_dot", "norm", "ormqr", "pca_lowrank", "pinv", "qr", "slogdet",
    "solve", "svd", "svdvals", "triangular_solve", "vecdot", "vector_norm",
    "matrix_norm",
]
