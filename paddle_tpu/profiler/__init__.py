"""paddle.profiler parity (`python/paddle/profiler/profiler.py:346`):
Profiler with scheduler states, RecordEvent scopes, chrome-trace export.

TPU-first: device timelines come from the jax/XLA profiler (xprof trace →
TensorBoard-compatible protobuf); host-side RecordEvent scopes are recorded
by this module and exported as chrome-tracing JSON (`export_chrome_tracing`
parity). The two can run together: jax.profiler captures kernels while the
host recorder captures python scopes.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum

from ..observability import metrics as _obs_metrics
from ..observability import trace as _obs_trace

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class _HostEventRecorder:
    """Host event ring (host_tracer.cc role)."""

    def __init__(self):
        self.events = []
        self.enabled = False
        self._lock = threading.Lock()

    def start(self):
        # rebind under the lock: a start() racing an in-flight add()
        # must not lose the append into the discarded old list
        with self._lock:
            self.events = []
        self.enabled = True

    def stop(self):
        self.enabled = False

    def add(self, name, t0, t1, tid):
        if self.enabled:
            with self._lock:
                self.events.append((name, t0, t1, tid))


_recorder = _HostEventRecorder()


class RecordEvent:
    """User scope marker (platform::RecordEvent parity).

    Doubles as the observability scope boundary: while the span is open,
    metrics recorded on this thread (and flight-recorder events / step
    records) are tagged ``scope=<name>`` — the RecordEvent ↔ telemetry
    integration from docs/OBSERVABILITY.md.  When the span tracer is
    buffering, every RecordEvent also lands as a span on the unified
    timeline (cat="user_scope"), so user scopes, flight instants, and
    step frames correlate in one Perfetto view."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._scope_token = None
        self._trace_span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        self._t0 = time.perf_counter_ns()
        self._scope_token = _obs_metrics.push_scope(self.name)
        self._trace_span = _obs_trace.begin(self.name, cat="user_scope")

    def end(self):
        _recorder.add(self.name, self._t0, time.perf_counter_ns(),
                      threading.get_ident())
        _obs_trace.end(self._trace_span)
        self._trace_span = None
        if self._scope_token is not None:
            _obs_metrics.pop_scope(self._scope_token)
            self._scope_token = None


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """State machine over step numbers (profiler.py:79 parity)."""

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if period == 0:
            # degenerate schedule (record=0 and nothing else): there is
            # never anything to record — CLOSED, not a perpetual RECORD
            return ProfilerState.CLOSED
        if repeat and s >= period * repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        prof._export_chrome(path)
        return path

    return handler


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else
            (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._jax_active = False
        self._jax_dir = None
        self.timer_only = timer_only

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self):
        self._state = self._scheduler(self._step)
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._begin_record()

    def _begin_record(self):
        _recorder.start()
        if not self.timer_only:
            try:
                import tempfile

                import jax

                self._jax_dir = tempfile.mkdtemp(prefix="xprof_")
                jax.profiler.start_trace(self._jax_dir)
                self._jax_active = True
            except Exception:
                self._jax_active = False

    def _end_record(self):
        _recorder.stop()
        if self._jax_active:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                # a trace that fails to stop means the xprof dump is
                # truncated/absent — count it so the missing artifact
                # is explainable from the metrics snapshot
                _obs_metrics.inc("profiler.stop_trace_errors")
            self._jax_active = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        old = self._state
        self._step += 1
        new = self._scheduler(self._step)
        if old in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and new in (ProfilerState.CLOSED, ProfilerState.READY):
            self._end_record()
        elif old in (ProfilerState.CLOSED, ProfilerState.READY) and \
                new in (ProfilerState.RECORD,
                        ProfilerState.RECORD_AND_RETURN):
            self._begin_record()
        self._state = new

    def stop(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._end_record()
        self._state = ProfilerState.CLOSED

    def _export_chrome(self, path):
        # Raw thread idents are huge unstable integers; Perfetto needs
        # small stable tids plus thread_name/process_name metadata
        # records or every scope collapses onto one unlabeled row.
        pid = os.getpid()
        tid_map = {}
        events = []
        for (name, t0, t1, raw_tid) in _recorder.events:
            tid = tid_map.get(raw_tid)
            if tid is None:
                tid = tid_map[raw_tid] = len(tid_map) + 1
            events.append({
                "name": name, "ph": "X", "pid": pid, "tid": tid,
                "ts": t0 / 1000.0, "dur": (t1 - t0) / 1000.0,
                "cat": "host",
            })
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": "paddle_tpu host"}}]
        for raw_tid, tid in sorted(tid_map.items(), key=lambda kv: kv[1]):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid,
                         "args": {"name": f"host-thread-{tid}"}})
            meta.append({"name": "thread_sort_index", "ph": "M",
                         "pid": pid, "tid": tid,
                         "args": {"sort_index": tid}})
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events,
                       "displayTimeUnit": "ms",
                       "xprof_dir": self._jax_dir}, f)
        return path

    def export(self, path, format="json"):
        return self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        agg = {}
        for name, t0, t1, _tid in _recorder.events:
            dur = (t1 - t0) / 1e6
            rec = agg.setdefault(name, [0, 0.0])
            rec[0] += 1
            rec[1] += dur
        lines = [f"{'name':<40} {'calls':>8} {'total(ms)':>12}"]
        for name, (calls, total) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40} {calls:>8} {total:>12.3f}")
        text = "\n".join(lines)
        print(text)
        return agg



class SortedKeys:
    """Summary-table sort keys (reference profiler.SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """Summary view selector (reference profiler.SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(profiler_result, file_name):
    """Persist a profiler result (reference export_protobuf writes the
    paddle profiler pb; this runtime's on-disk trace format is
    chrome-trace JSON — same information, readable by chrome://tracing
    and perfetto). The file extension is honored as given."""
    return profiler_result.export(file_name) \
        if hasattr(profiler_result, "export") else None
