"""paddle.device parity (`python/paddle/device/`): device query/selection.

On the jax runtime, placement is sharding-driven; set_device is advisory.
Streams/events are no-ops — XLA owns scheduling (the reference's stream
analyzer role, `new_executor/interpreter/stream_analyzer.cc`, is subsumed by
the compiler).
"""
from __future__ import annotations

import jax

_current = None


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_custom_device_type():
    return ["tpu"]


def get_available_device():
    return get_all_devices()


def get_device():
    global _current
    if _current is None:
        d = jax.devices()[0]
        _current = f"{d.platform}:{d.id}"
    return _current


def set_device(device):
    global _current
    _current = device
    return device


def device_count():
    return jax.device_count()


def is_compiled_with_cinn():
    return False


# --- memory stats (paddle/fluid/memory/stats.h role) -------------------------

def _resolve_device(device=None):
    devs = jax.local_devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str) and ":" in device:
        idx = int(device.rsplit(":", 1)[1])
        for d in devs:
            if d.id == idx:
                return d
        return devs[idx % len(devs)]
    return devs[0]


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats for one device (reference:
    `paddle/fluid/memory/stats.h` DEVICE_MEMORY_STAT_* counters). Keys
    include `bytes_in_use`, `peak_bytes_in_use`, `largest_alloc_size`,
    and (TPU) `bytes_limit`. Empty dict when the backend doesn't report
    (e.g. CPU)."""
    d = _resolve_device(device)
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (memory_allocated parity)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """High-watermark of allocated bytes (max_memory_allocated parity).
    PJRT tracks the peak since process start; there is no reset API."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator (pool size; falls back to
    bytes_in_use on backends without a reservation pool)."""
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


def total_memory(device=None) -> int:
    """Device memory capacity in bytes (0 when unreported)."""
    return int(memory_stats(device).get("bytes_limit", 0))


class Stream:
    """No-op stream (XLA schedules async execution itself)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        pass


def current_stream(device=None):
    return Stream(device)


def synchronize(device=None):
    for d in jax.local_devices():
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception:
            from ..observability import metrics as _metrics

            _metrics.inc("device.sync_errors")


class cuda:  # namespace shim: reference exposes paddle.device.cuda
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    # memory-stat API parity (paddle.device.cuda.max_memory_allocated):
    # reports the accelerator this process actually runs on
    memory_allocated = staticmethod(
        lambda device=None: memory_allocated(device))
    max_memory_allocated = staticmethod(
        lambda device=None: max_memory_allocated(device))
    memory_reserved = staticmethod(
        lambda device=None: memory_reserved(device))
    max_memory_reserved = staticmethod(
        lambda device=None: max_memory_reserved(device))



def get_cudnn_version():
    return None  # no cuDNN tier on TPU


class XPUPlace:
    def __init__(self, id=0):
        raise NotImplementedError(
            "XPU is a second-vendor backend subsumed by PJRT here "
            "(README Scope notes)")


class IPUPlace:
    def __init__(self, id=0):
        raise NotImplementedError(
            "IPU is not a target of this build (README Scope notes)")


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_distribute():
    return True  # XLA collectives are always in


def is_compiled_with_custom_device(device_type=None):
    return False


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_custom_device():
    return []


def set_stream(stream=None):
    """XLA owns stream scheduling; returns the current (no-op) stream."""
    return current_stream()


import contextlib as _contextlib


@_contextlib.contextmanager
def stream_guard(stream=None):
    yield current_stream()


# ---- custom device plugins (PJRT) ----------------------------------------

def register_pjrt_plugin(name, library_path, options=None, priority=400):
    """Register an out-of-tree device backend from a PJRT plugin shared
    library.

    Role parity: the reference's pluggable-device ABI
    (`paddle/phi/backends/device_ext.h:94` C_DeviceInterface +
    `paddle/phi/backends/custom/custom_device.cc`) — a vendor ships one
    shared library and the framework discovers a new device type at
    runtime. TPU-first collapse: PJRT *is* that ABI here; this registers
    the plugin with the runtime so `jax.devices(name)` /
    `set_device(name)` can target it. Must be called before the first
    device computation (backends are frozen at first use).
    """
    import os

    if not os.path.exists(library_path):
        raise FileNotFoundError(
            f"PJRT plugin library not found: {library_path}")
    from jax._src import xla_bridge as _xb

    return _xb.register_plugin(name, library_path=library_path,
                               options=options, priority=priority)


def get_registered_backends():
    """Names of every registered PJRT backend factory (built-in + custom
    plugins) — the custom-device discovery surface
    (`paddle.device.get_all_custom_device_type` over real plugins)."""
    from jax._src import xla_bridge as _xb

    return sorted(_xb._backend_factories)
