"""paddle.device parity (`python/paddle/device/`): device query/selection.

On the jax runtime, placement is sharding-driven; set_device is advisory.
Streams/events are no-ops — XLA owns scheduling (the reference's stream
analyzer role, `new_executor/interpreter/stream_analyzer.cc`, is subsumed by
the compiler).
"""
from __future__ import annotations

import jax

_current = None


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_custom_device_type():
    return ["tpu"]


def get_available_device():
    return get_all_devices()


def get_device():
    global _current
    if _current is None:
        d = jax.devices()[0]
        _current = f"{d.platform}:{d.id}"
    return _current


def set_device(device):
    global _current
    _current = device
    return device


def device_count():
    return jax.device_count()


def is_compiled_with_cinn():
    return False


class Stream:
    """No-op stream (XLA schedules async execution itself)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        pass


def current_stream(device=None):
    return Stream(device)


def synchronize(device=None):
    for d in jax.local_devices():
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception:
            pass


class cuda:  # namespace shim: reference exposes paddle.device.cuda
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False
