"""Optimizer base + the update-rule zoo.

Role parity: `python/paddle/optimizer/optimizer.py` (Optimizer base,
accumulators, multi-precision master weights) + per-optimizer kernels
(`paddle/phi/kernels/gpu/adam_kernel.cu` etc).

TPU-first split: every optimizer defines two pure functions —
`init_slots(param)` and `update(param, grad, slots, lr, t)` — which are the
single source of truth for both the eager `.step()` (dispatched through the
op layer, so the whole update is one fused XLA computation) and the
functional `apply_gradients` used by jit'd/sharded train steps (where ZeRO
recipes shard `slots` over the dp axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    # subclasses set: _slot_names
    _slot_names = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._per_param_wd = {}  # id(param) -> weight-decay coeff override
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                self._param_groups = parameters
                flat = []
                for g in parameters:
                    plist = list(g["params"])
                    # group learning_rate is a multiplier on the base lr
                    # (ParamAttr.learning_rate semantics); weight_decay is a
                    # per-group coefficient override
                    if "learning_rate" in g:
                        for p in plist:
                            p.optimize_attr["learning_rate"] = float(
                                g["learning_rate"])
                    if "weight_decay" in g:
                        wd = g["weight_decay"]
                        coeff = wd.coeff if hasattr(wd, "coeff") else float(wd)
                        for p in plist:
                            self._per_param_wd[id(p)] = coeff
                    flat.extend(plist)
                parameters = flat
            else:
                self._param_groups = None
        else:
            self._param_groups = None
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators = {}  # id(param) -> dict slot->jax array
        self._master_weights = {}  # id(param) -> fp32 array
        self._step_count = 0

    # --- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # --- pure update rule (override) ----------------------------------------
    def init_slots(self, param_value):
        """Return dict slot_name -> initial jax array for one param."""
        return {}

    def update(self, p, g, slots, lr, t, wd):
        """Pure: returns (new_p, new_slots). p/g fp32."""
        raise NotImplementedError

    def _functional_wd(self):
        """Uniform weight-decay coeff for the functional pytree path."""
        return self._weight_decay.coeff if isinstance(
            self._weight_decay, L2Decay) else 0.0

    # --- functional API (jit / sharded path) ---------------------------------
    def init_state(self, params):
        """params: pytree of arrays -> state pytree (slots + step)."""
        slots = jax.tree_util.tree_map(
            lambda p: self.init_slots(p), params,
            is_leaf=lambda x: hasattr(x, "shape"))
        return {"slots": slots, "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, state, lr=None):
        """Pure functional update over pytrees; usable under jit/shard."""
        lr = self.get_lr() if lr is None else lr
        t = state["step"] + 1
        wd = self._functional_wd()

        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_g = tree.flatten_up_to(grads)
        flat_s = tree.flatten_up_to(state["slots"])
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            np_, ns_ = self.update(p32, g32, s, lr, t, wd)
            new_p.append(np_.astype(p.dtype))
            new_s.append(ns_)
        return (tree.unflatten(new_p),
                {"slots": tree.unflatten(new_s), "step": t})

    # --- eager path ----------------------------------------------------------
    def _get_slots(self, p):
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self.init_slots(p._value)
        return self._accumulators[key]

    def _master(self, p):
        if not self._multi_precision or p._value.dtype == jnp.float32:
            return None
        key = id(p)
        if key not in self._master_weights:
            self._master_weights[key] = p._value.astype(jnp.float32)
        return self._master_weights[key]

    @property
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        return self._parameter_list

    def _wd_for(self, p):
        """Per-param weight-decay coefficient (group overrides, exclusion
        fns in subclasses)."""
        if id(p) in self._per_param_wd:
            return self._per_param_wd[id(p)]
        return self._weight_decay.coeff if isinstance(
            self._weight_decay, L2Decay) else 0.0

    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._params
                        if not p.stop_gradient and p.grad is not None
                        and getattr(p, "trainable", True)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        t = self._step_count
        for p, g in params_grads:
            wd = self._wd_for(p)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr
            slots = self._get_slots(p)
            master = self._master(p)
            slot_names = sorted(slots)
            slot_vals = [slots[k] for k in slot_names]

            def f(pv, gv, mv, *sv):
                base = mv if mv is not None else pv.astype(jnp.float32)
                g32 = gv.astype(jnp.float32)
                new_p, new_slots = self.update(
                    base, g32, dict(zip(slot_names, sv)), plr, t, wd)
                outs = [new_p.astype(pv.dtype)]
                if mv is not None:
                    outs.append(new_p)
                outs.extend(new_slots[k] for k in slot_names)
                return tuple(outs)

            g_val = g._value if isinstance(g, Tensor) else g
            res = f(p._value, g_val, master, *slot_vals)
            i = 0
            p._value = res[i]; i += 1
            if master is not None:
                self._master_weights[id(p)] = res[i]; i += 1
            for k in slot_names:
                slots[k] = res[i]; i += 1

    def clear_grad(self, set_to_zero=True):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..core import flags

        if flags.in_static_mode():
            from ..static import minimize_static

            return minimize_static(self, loss, parameters=parameters,
                                   no_grad_set=no_grad_set)
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # --- state dict ----------------------------------------------------------
    def state_dict(self):
        out = {}
        name_of = {id(p): (p.name or f"param_{i}")
                   for i, p in enumerate(self._params)}
        for pid, slots in self._accumulators.items():
            base = name_of.get(pid, str(pid))
            for k, v in slots.items():
                out[f"{base}.{k}"] = Tensor(v)
        for pid, mw in self._master_weights.items():
            out[f"{name_of.get(pid, str(pid))}.master_weight"] = Tensor(mw)
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        name_of = {(p.name or f"param_{i}"): p
                   for i, p in enumerate(self._params)}
        self._step_count = int(state.get("@step", 0))
        if "LR_Scheduler" in state and isinstance(self._learning_rate,
                                                  LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        for k, v in state.items():
            if k in ("@step", "LR_Scheduler"):
                continue
            base, slot = k.rsplit(".", 1)
            p = name_of.get(base)
            if p is None:
                continue
            val = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if slot == "master_weight":
                self._master_weights[id(p)] = val
            else:
                self._get_slots(p)[slot] = val


class SGD(Optimizer):
    def init_slots(self, pv):
        return {}

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_slots(self, pv):
        return {"velocity": jnp.zeros(pv.shape, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_slots(self, pv):
        return {"moment": jnp.full(pv.shape, self._init_acc, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        m = slots["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def init_slots(self, pv):
        s = {"moment1": jnp.zeros(pv.shape, jnp.float32),
             "moment2": jnp.zeros(pv.shape, jnp.float32)}
        if self._amsgrad:
            s["moment2_max"] = jnp.zeros(pv.shape, jnp.float32)
        return s

    def _decay(self, p, g, lr, wd):
        # plain Adam treats decay as L2 regularization added to the gradient
        return (g + wd * p) if wd else g, p

    def update(self, p, g, slots, lr, t, wd):
        g, p = self._decay(p, g, lr, wd)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * g * g
        t_f = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t_f)
        if self._amsgrad:
            vmax = jnp.maximum(slots["moment2_max"], v)
            vhat = vmax / (1 - b2 ** t_f)
            new_slots = {"moment1": m, "moment2": v, "moment2_max": vmax}
        else:
            vhat = v / (1 - b2 ** t_f)
            new_slots = {"moment1": m, "moment2": v}
        p_new = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        p_new = self._post(p_new, p, lr, wd)
        return p_new, new_slots

    def _post(self, p_new, p_old, lr, wd):
        return p_new


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, amsgrad,
                         name)
        self._coeff = weight_decay if isinstance(weight_decay, float) else \
            getattr(weight_decay, "coeff", 0.01)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._weight_decay = L2Decay(self._coeff)  # for functional wd plumb

    def _wd_for(self, p):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return super()._wd_for(p)

    def _decay(self, p, g, lr, wd):
        return g, p  # decoupled: no grad modification

    def update(self, p, g, slots, lr, t, wd):
        # decoupled weight decay applied to the parameter directly
        p = p * (1.0 - lr * wd) if wd else p
        return super().update(p, g, slots, lr, t, 0.0)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def init_slots(self, pv):
        return {"moment": jnp.zeros(pv.shape, jnp.float32),
                "inf_norm": jnp.zeros(pv.shape, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        t_f = jnp.asarray(t, jnp.float32)
        p_new = p - lr / (1 - self._beta1 ** t_f) * m / (u + self._epsilon)
        return p_new, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_slots(self, pv):
        s = {"mean_square": jnp.zeros(pv.shape, jnp.float32),
             "momentum": jnp.zeros(pv.shape, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros(pv.shape, jnp.float32)
        return s

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            new = {"mean_square": ms, "mean_grad": mg}
        else:
            denom = jnp.sqrt(ms + self._epsilon)
            new = {"mean_square": ms}
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new["momentum"] = mom
        return p - mom, new


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _wd_for(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._wd

    def _functional_wd(self):
        return self._wd

    def init_slots(self, pv):
        return {"moment1": jnp.zeros(pv.shape, jnp.float32),
                "moment2": jnp.zeros(pv.shape, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * g * g
        t_f = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t_f)
        vhat = v / (1 - b2 ** t_f)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * p
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._epsilon = epsilon
        self._rho = rho

    def init_slots(self, pv):
        return {"avg_squared_grad": jnp.zeros(pv.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(pv.shape, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * slots["avg_squared_update"] + \
            (1 - self._rho) * upd * upd
        return p - lr * upd, {"avg_squared_grad": asg,
                              "avg_squared_update": asu}


class Rprop(Optimizer):
    """Resilient backprop (reference optimizer/rprop.py): per-element
    step sizes grown/shrunk by gradient-sign agreement; sign-based
    update (batch-mode only, as the reference documents)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def init_slots(self, pv):
        return {"delta": jnp.full(pv.shape, self.get_lr(), jnp.float32),
                "prev_grad": jnp.zeros(pv.shape, jnp.float32)}

    def update(self, p, g, slots, lr, t, wd):
        g = g.astype(jnp.float32)
        sign = jnp.sign(g * slots["prev_grad"])
        delta = jnp.clip(
            jnp.where(sign > 0, slots["delta"] * self._eta_pos,
                      jnp.where(sign < 0, slots["delta"] * self._eta_neg,
                                slots["delta"])),
            self._lr_min, self._lr_max)
        # on sign flip: no step, zero the remembered grad
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * delta
        return new_p, {"delta": delta, "prev_grad": g_eff}


class LBFGS(Optimizer):
    """L-BFGS with two-loop recursion + Armijo backtracking line search
    (reference optimizer/lbfgs.py). Requires `step(closure)` — the
    closure re-evaluates the loss (and grads) like the reference API."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=10, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist = []
        self._y_hist = []
        self._prev_flat_g = None

    def _flat_params(self):
        return jnp.concatenate([p._value.astype(jnp.float32).reshape(-1)
                                for p in self._parameter_list])

    def _flat_grads(self):
        return jnp.concatenate([
            (p.grad._value if p.grad is not None
             else jnp.zeros(p._value.shape)).astype(jnp.float32).reshape(-1)
            for p in self._parameter_list])

    def _assign_flat(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape)) if p.shape else 1
            p._value = flat[off:off + n].reshape(tuple(p.shape)) \
                .astype(p._value.dtype)
            off += n

    def step(self, closure=None):
        assert closure is not None, \
            "LBFGS.step(closure) needs a loss closure (reference API)"

        def eval_loss_grads():
            self.clear_grad()
            loss = closure()
            return float(loss.numpy() if hasattr(loss, "numpy") else loss)

        loss = eval_loss_grads()
        for _ in range(self.max_iter):
            g = self._flat_grads()
            if float(jnp.max(jnp.abs(g))) < self.tol_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y in reversed(list(zip(self._s_hist, self._y_hist))):
                rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
                a = rho * jnp.vdot(s, q)
                alphas.append((a, rho, s, y))
                q = q - a * y
            if self._y_hist:
                y_l, s_l = self._y_hist[-1], self._s_hist[-1]
                gamma = jnp.vdot(s_l, y_l) / jnp.maximum(
                    jnp.vdot(y_l, y_l), 1e-10)
                q = q * gamma
            for a, rho, s, y in reversed(alphas):
                b = rho * jnp.vdot(y, q)
                q = q + s * (a - b)
            d = -q
            # Armijo backtracking
            x0 = self._flat_params()
            g0_d = float(jnp.vdot(g, d))
            t = self.get_lr()
            ok = False
            for _ls in range(20):
                self._assign_flat(x0 + t * d)
                new_loss = eval_loss_grads()
                if new_loss <= loss + 1e-4 * t * g0_d:
                    ok = True
                    break
                t *= 0.5
            if not ok:
                self._assign_flat(x0)
                eval_loss_grads()
                break
            s_vec = t * d
            new_g = self._flat_grads()
            y_vec = new_g - g
            if float(jnp.vdot(s_vec, y_vec)) > 1e-10:
                self._s_hist.append(s_vec)
                self._y_hist.append(y_vec)
                if len(self._s_hist) > self.history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
            if abs(new_loss - loss) < self.tol_change:
                loss = new_loss
                break
            loss = new_loss
        return loss
