"""paddle.save / paddle.load parity (`python/paddle/framework/io.py:721,960`).

Serialization: numpy-backed pickle for state dicts (cross-version stable),
with nested dict/list structures preserved. Program/jit artifacts are handled
by `paddle_tpu.jit.save`.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), str(obj._value.dtype))
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_storable(v) for v in obj)
    return obj


def _from_storable(obj):
    if isinstance(obj, _TensorPayload):
        return Tensor(obj.data, dtype=obj.dtype)
    if isinstance(obj, dict):
        return {k: _from_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_storable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("data", "dtype")

    def __init__(self, data, dtype):
        # bfloat16 has no numpy wire format -> store as uint16 view
        if dtype == "bfloat16":
            self.data = data.view(np.uint16)
        else:
            self.data = data
        self.dtype = dtype

    def __reduce__(self):
        return (_restore_payload, (self.data, self.dtype))


def _restore_payload(data, dtype):
    p = object.__new__(_TensorPayload)
    if dtype == "bfloat16":
        import jax.numpy as jnp

        p.data = data.view(jnp.bfloat16)
    else:
        p.data = data
    p.dtype = dtype
    return p


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _from_storable(pickle.load(f))
