from ..core import rng as _rng


def get_cuda_rng_state():
    return _rng.get_rng_state()


def set_cuda_rng_state(state):
    _rng.set_rng_state(state)
