"""paddle.framework parity surface (dtype helpers, save/load, seeds)."""
from ..core.dtypes import convert_dtype, get_default_dtype, set_default_dtype  # noqa: F401
from ..core.rng import seed  # noqa: F401
from .io_utils import load, save  # noqa: F401
from .random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
