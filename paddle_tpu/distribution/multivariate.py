"""Multivariate / vector-event distributions.

Role parity: `python/paddle/distribution/{categorical,dirichlet,multinomial,
multivariate_normal}.py`.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.dispatch import apply
from .distribution import Distribution, _param, _sample_shape
from .exponential_family import ExponentialFamily


class Categorical(Distribution):
    """Categorical over the last axis of `logits`.

    Ref: python/paddle/distribution/categorical.py. The reference mixes two
    conventions (probs normalizes by sum `categorical.py:120`, KL uses
    softmax `categorical.py:218-224`); this build uses log-space softmax
    semantics consistently."""

    def __init__(self, logits, name=None):
        self.logits = _param(logits)
        shape = jnp.shape(self.logits._value)
        super().__init__(shape[:-1], ())
        self._num_events = shape[-1]

    @property
    def probs(self):
        def f(lg):
            p = lg - jax.scipy.special.logsumexp(lg, axis=-1, keepdims=True)
            return jnp.exp(p)

        return apply("categorical.probs", f, self.logits)

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = _sample_shape(shape) + self._batch_shape

        def f(lg):
            return jax.random.categorical(key, lg, axis=-1, shape=out_shape)

        return apply("categorical.sample", f, self.logits).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, lg):
            lp = lg - jsp.logsumexp(lg, axis=-1, keepdims=True)
            return jnp.take_along_axis(
                lp, v[..., None].astype(jnp.int32), axis=-1)[..., 0]

        return apply("categorical.log_prob", f, value, self.logits)

    def entropy(self):
        def f(lg):
            lp = lg - jsp.logsumexp(lg, axis=-1, keepdims=True)
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

        return apply("categorical.entropy", f, self.logits)

    def kl_divergence_categorical(self, other):
        def f(lg, og):
            lp = lg - jsp.logsumexp(lg, axis=-1, keepdims=True)
            lq = og - jsp.logsumexp(og, axis=-1, keepdims=True)
            return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)

        return apply("categorical.kl", f, self.logits, other.logits)


class Multinomial(Distribution):
    """Multinomial(total_count, probs).
    Ref: python/paddle/distribution/multinomial.py."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _param(probs)
        shape = jnp.shape(self.probs._value)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        n = self.total_count
        return apply("multinomial.mean", lambda p: n * p, self.probs)

    @property
    def variance(self):
        n = self.total_count
        return apply("multinomial.var", lambda p: n * p * (1 - p), self.probs)

    def sample(self, shape=()):
        key = self._next_key()
        n = self.total_count
        out_batch = _sample_shape(shape) + self._batch_shape

        def f(p):
            k = p.shape[-1]
            lp = jnp.log(jnp.maximum(p, jnp.finfo(jnp.float32).tiny))
            draws = jax.random.categorical(
                key, lp, axis=-1, shape=(n,) + out_batch)
            one_hot = jax.nn.one_hot(draws, k, dtype=jnp.result_type(float))
            return jnp.sum(one_hot, axis=0)

        return apply("multinomial.sample", f, self.probs).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            logc = (jsp.gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(jsp.gammaln(v + 1), -1))
            return logc + jnp.sum(jsp.xlogy(v, p), -1)

        return apply("multinomial.log_prob", f, value, self.probs)

    def entropy(self):
        # sum of per-category binomial entropies minus covariance correction
        # is an approximation; the reference computes entropy by exhaustive
        # support enumeration, feasible only for tiny (n, k) — do the same.
        n = self.total_count

        def f(p):
            k = p.shape[-1]
            # enumeration visits (n+1)**k tuples; bound that, not n*k
            if (n + 1) ** k > 4096:
                raise NotImplementedError(
                    "Multinomial.entropy: support too large to enumerate")
            import itertools

            import numpy as _np

            support = [c for c in itertools.product(range(n + 1), repeat=k)
                       if sum(c) == n]
            v = jnp.asarray(_np.array(support, dtype=_np.float32))
            logc = (jsp.gammaln(jnp.asarray(float(n)) + 1)
                    - jnp.sum(jsp.gammaln(v + 1), -1))
            lp = logc + jnp.sum(jsp.xlogy(v, p[..., None, :]), -1)
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

        return apply("multinomial.entropy", f, self.probs)


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration).
    Ref: python/paddle/distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _param(concentration)
        shape = jnp.shape(self.concentration._value)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return apply("dirichlet.mean",
                     lambda c: c / jnp.sum(c, -1, keepdims=True),
                     self.concentration)

    @property
    def variance(self):
        def f(c):
            a0 = jnp.sum(c, -1, keepdims=True)
            return c * (a0 - c) / (a0 * a0 * (a0 + 1))

        return apply("dirichlet.var", f, self.concentration)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(c):
            g = jax.random.gamma(key, jnp.broadcast_to(c, out_shape))
            return g / jnp.sum(g, -1, keepdims=True)

        return apply("dirichlet.rsample", f, self.concentration)

    def log_prob(self, value):
        def f(v, c):
            return (jnp.sum(jsp.xlogy(c - 1, v), -1)
                    + jsp.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jsp.gammaln(c), -1))

        return apply("dirichlet.log_prob", f, value, self.concentration)

    def entropy(self):
        def f(c):
            a0 = jnp.sum(c, -1)
            k = c.shape[-1]
            return (jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(a0)
                    + (a0 - k) * jsp.digamma(a0)
                    - jnp.sum((c - 1) * jsp.digamma(c), -1))

        return apply("dirichlet.entropy", f, self.concentration)


class MultivariateNormal(Distribution):
    """MVN(loc, covariance_matrix | scale_tril).
    Ref: python/paddle/distribution/multivariate_normal.py."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _param(loc)
        if scale_tril is not None:
            self.scale_tril = _param(scale_tril)
        elif covariance_matrix is not None:
            cov = _param(covariance_matrix)
            self.scale_tril = apply("mvn.chol", jnp.linalg.cholesky, cov)
        elif precision_matrix is not None:
            prec = _param(precision_matrix)

            def inv_chol(p):
                return jnp.linalg.cholesky(jnp.linalg.inv(p))

            self.scale_tril = apply("mvn.prec_chol", inv_chol, prec)
        else:
            raise ValueError(
                "one of covariance_matrix/precision_matrix/scale_tril "
                "must be specified")
        d = jnp.shape(self.loc._value)[-1]
        batch = jnp.broadcast_shapes(
            jnp.shape(self.loc._value)[:-1],
            jnp.shape(self.scale_tril._value)[:-2])
        super().__init__(batch, (d,))

    @property
    def covariance_matrix(self):
        def f(L):
            return L @ jnp.swapaxes(L, -1, -2)

        return apply("mvn.cov", f, self.scale_tril)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def f(L):
            return jnp.sum(L * L, axis=-1)

        return apply("mvn.var", f, self.scale_tril)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(l, L):
            eps = jax.random.normal(key, out_shape, jnp.result_type(float))
            return l + jnp.einsum("...ij,...j->...i", L, eps)

        return apply("mvn.rsample", f, self.loc, self.scale_tril)

    def log_prob(self, value):
        def f(v, l, L):
            d = v.shape[-1]
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(
                L, diff[..., None], lower=True)[..., 0]
            m = jnp.sum(sol * sol, -1)
            half_logdet = jnp.sum(jnp.log(
                jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return -0.5 * (d * math.log(2 * math.pi) + m) - half_logdet

        return apply("mvn.log_prob", f, value, self.loc, self.scale_tril)

    def entropy(self):
        def f(l, L):
            d = l.shape[-1]
            half_logdet = jnp.sum(jnp.log(
                jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet

        return apply("mvn.entropy", f, self.loc, self.scale_tril)
