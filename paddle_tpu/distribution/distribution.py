"""Distribution base class.

Role parity: `python/paddle/distribution/distribution.py` (Distribution with
batch_shape/event_shape, sample/rsample/log_prob/prob/entropy surface).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.rng import default_generator
from ..core.tensor import Tensor


def _asval(x, dtype=None):
    """Unwrap Tensor / python scalar into a jnp array (keeps tracers)."""
    if isinstance(x, Tensor):
        v = x._value
    elif isinstance(x, (int, float, bool, list, tuple, np.ndarray)):
        v = jnp.asarray(x, dtype=dtype or jnp.float32)
    else:
        v = x
    if dtype is not None and v.dtype != jnp.dtype(dtype):
        v = v.astype(dtype)
    return v


def _param(x):
    """Distribution parameter → Tensor (gradient-capable handle)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(_asval(x))


def _sample_shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base of all distributions; subclasses implement the pure-jnp kernels
    `_log_prob(value, *params)` etc. and declare `_param_names`."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    # --- to be provided by subclasses ---------------------------------------
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        """Non-reparameterized sample (gradients stopped)."""
        s = self.rsample(shape)
        return s.detach() if isinstance(s, Tensor) else s

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply("dist.prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # --- helpers ------------------------------------------------------------
    def _next_key(self):
        return default_generator.split()

    def _extend_shape(self, sample_shape):
        return (_sample_shape(sample_shape) + self._batch_shape
                + self._event_shape)

    @property
    def stddev(self):
        return apply("dist.stddev", jnp.sqrt, self.variance)

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")
