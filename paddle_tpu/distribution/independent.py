"""Independent: reinterpret batch dims as event dims.

Role parity: `python/paddle/distribution/independent.py`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from .distribution import Distribution


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = base.batch_shape + base.event_shape
        n = len(base.batch_shape) - self.reinterpreted_batch_rank
        super().__init__(shape[:n],
                         shape[n:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_event(self, x):
        k = self.reinterpreted_batch_rank

        def f(v):
            return jnp.sum(v, axis=tuple(range(-k, 0)))

        return apply("independent.sum", f, x)

    def log_prob(self, value):
        return self._sum_event(self.base.log_prob(value))

    def entropy(self):
        return self._sum_event(self.base.entropy())
