"""paddle_tpu.distribution: probability distributions, transforms, KL.

Role parity: `python/paddle/distribution/` (Distribution base
`python/paddle/distribution/distribution.py`, kl registry `kl.py`,
transforms `transform.py`). TPU-first: every density/statistic is a pure
jnp function dispatched through the framework op gate, so log_prob/rsample
are differentiable on the eager tape and trace cleanly under jit; sampling
uses the functional PRNG (threefry keys from `core.rng`), never host RNG.
"""
from .distribution import Distribution  # noqa: F401
from .exponential_family import ExponentialFamily  # noqa: F401
from .univariate import (  # noqa: F401
    Bernoulli, Beta, Binomial, Cauchy, ContinuousBernoulli, Exponential,
    Gamma, Geometric, Gumbel, Laplace, LogNormal, Normal, Poisson, StudentT,
    Uniform,
)
from .multivariate import (  # noqa: F401
    Categorical, Dirichlet, Multinomial, MultivariateNormal,
)
from .independent import Independent  # noqa: F401
from .transform import (  # noqa: F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform,
)
from .transformed_distribution import TransformedDistribution  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "ExponentialFamily",
    "Bernoulli", "Beta", "Binomial", "Categorical", "Cauchy",
    "ContinuousBernoulli", "Dirichlet", "Exponential", "Gamma", "Geometric",
    "Gumbel", "Independent", "Laplace", "LogNormal", "Multinomial",
    "MultivariateNormal", "Normal", "Poisson", "StudentT", "Uniform",
    "TransformedDistribution",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "kl_divergence", "register_kl",
]
