"""Bijective transforms with log-det-Jacobian tracking.

Role parity: `python/paddle/distribution/transform.py` (Transform base with
forward/inverse/forward_log_det_jacobian, the zoo of Abs/Affine/Chain/Exp/
Independent/Power/Reshape/Sigmoid/Softmax/Stack/StickBreaking/Tanh).
TPU-first: each transform is a pure jnp bijector; ldj of arbitrary
user-defined forward maps could lean on jax.jacfwd, but the zoo ships
closed forms.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

# variable "type" markers (reference's variable.Independent/Real domain tags)


class _Domain:
    def __init__(self, event_rank=0):
        self.event_rank = event_rank


real = _Domain(0)


class Transform:
    """Base transform. Subclasses implement `_forward`, `_inverse`,
    `_forward_log_det_jacobian` as pure-jnp functions."""

    _domain = real
    _codomain = real

    # event dims consumed/produced (0 for elementwise)
    _event_rank = 0

    @property
    def domain(self):
        return self._domain

    @property
    def codomain(self):
        return self._codomain

    def forward(self, x):
        return apply(f"{type(self).__name__}.fwd", self._forward, x)

    def inverse(self, y):
        return apply(f"{type(self).__name__}.inv", self._inverse, y)

    def forward_log_det_jacobian(self, x):
        return apply(f"{type(self).__name__}.fldj",
                     self._forward_log_det_jacobian, x)

    def inverse_log_det_jacobian(self, y):
        def f(yv):
            return -self._forward_log_det_jacobian(self._inverse(yv))

        return apply(f"{type(self).__name__}.ildj", f, y)

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def __call__(self, x):
        return self.forward(x)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class AbsTransform(Transform):
    """y = |x| (not bijective; inverse returns the positive branch, as the
    reference does)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("AbsTransform is not injective")


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(loc)
        self.scale = scale if isinstance(scale, Tensor) else Tensor(scale)

    def forward(self, x):
        return apply("Affine.fwd", lambda xv, l, s: l + s * xv,
                     x, self.loc, self.scale)

    def inverse(self, y):
        return apply("Affine.inv", lambda yv, l, s: (yv - l) / s,
                     y, self.loc, self.scale)

    def forward_log_det_jacobian(self, x):
        def f(xv, l, s):
            return jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                    jnp.broadcast_shapes(jnp.shape(xv),
                                                         jnp.shape(s)))

        return apply("Affine.fldj", f, x, self.loc, self.scale)

    def inverse_log_det_jacobian(self, y):
        def f(yv, l, s):
            return jnp.broadcast_to(-jnp.log(jnp.abs(s)),
                                    jnp.broadcast_shapes(jnp.shape(yv),
                                                         jnp.shape(s)))

        return apply("Affine.ildj", f, y, self.loc, self.scale)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = power if isinstance(power, Tensor) else Tensor(power)

    def forward(self, x):
        return apply("Power.fwd", lambda xv, p: jnp.power(xv, p),
                     x, self.power)

    def inverse(self, y):
        return apply("Power.inv", lambda yv, p: jnp.power(yv, 1.0 / p),
                     y, self.power)

    def forward_log_det_jacobian(self, x):
        def f(xv, p):
            return jnp.log(jnp.abs(p * jnp.power(xv, p - 1)))

        return apply("Power.fldj", f, x, self.power)

    def inverse_log_det_jacobian(self, y):
        def f(yv, p):
            xv = jnp.power(yv, 1.0 / p)
            return -jnp.log(jnp.abs(p * jnp.power(xv, p - 1)))

        return apply("Power.ildj", f, y, self.power)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """x → softmax over last axis (not bijective; ldj undefined, the
    reference raises the same way)."""

    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("SoftmaxTransform has no log-det-jacobian")


class StickBreakingTransform(Transform):
    """R^{K-1} → simplex^K via stick breaking."""

    _event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return jnp.concatenate([z, pad], -1) * jnp.concatenate([pad, zc], -1)

    def _inverse(self, y):
        k = y.shape[-1] - 1
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        sf = 1 - jnp.cumsum(y[..., :-1], axis=-1)
        sf_shift = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), sf[..., :-1]], -1)
        z = y[..., :-1] / sf_shift
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc_prev = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype),
             jnp.cumprod(1 - z, axis=-1)[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(zc_prev), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(
                np.prod(self.out_event_shape)):
            raise ValueError("in/out event sizes must match")
        self._event_rank = len(self.in_event_shape)
        self._event_rank_out = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class IndependentTransform(Transform):
    """Promote `reinterpreted_batch_rank` batch dims of the base transform
    into event dims (sums the ldj over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)

    @property
    def _event_rank(self):
        return self.base._event_rank + self.reinterpreted_batch_rank

    @property
    def _event_rank_out(self):
        in_r = self.base._event_rank
        out_r = getattr(self.base, "_event_rank_out", in_r)
        return out_r + self.reinterpreted_batch_rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)

        def f(l):
            axes = tuple(range(-self.reinterpreted_batch_rank, 0))
            return jnp.sum(l, axis=axes)

        return apply("IndependentT.sum", f, ldj)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    @property
    def _event_rank(self):
        # event rank required at the chain input (backward accumulation)
        r = 0
        for t in reversed(self.transforms):
            in_r = t._event_rank
            out_r = getattr(t, "_event_rank_out", in_r)
            r = max(r - (out_r - in_r), in_r)
        return r

    @property
    def _event_rank_out(self):
        r = self._event_rank
        for t in self.transforms:
            in_r = t._event_rank
            out_r = getattr(t, "_event_rank_out", in_r)
            r = r - in_r + out_r
        return r

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        # each term is reduced over the event dims it does not own, so
        # elementwise and event-rank transforms mix into one batch-shaped
        # total (an elementwise ldj inside an event-rank-1 chain must be
        # summed over the event axis, not broadcast-added)
        cur = self._event_rank
        total = None
        for t in self.transforms:
            in_r = t._event_rank
            out_r = getattr(t, "_event_rank_out", in_r)
            ldj = t.forward_log_det_jacobian(x)
            k = cur - in_r
            if k > 0:
                ldj = apply(
                    "Chain.reduce",
                    lambda l, k=k: jnp.sum(l, axis=tuple(range(-k, 0))), ldj)
            total = ldj if total is None else apply(
                "Chain.add", jnp.add, total, ldj)
            x = t.forward(x)
            cur = cur - in_r + out_r
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, x, method):
        from .. import ops

        parts = ops.unbind(x, self.axis)
        outs = [getattr(t, method)(p)
                for t, p in zip(self.transforms, parts)]
        return ops.stack(outs, self.axis)

    def forward(self, x):
        return self._map(x, "forward")

    def inverse(self, y):
        return self._map(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")
