"""Exponential-family base: entropy via Bregman identity on the log-normalizer.

Role parity: `python/paddle/distribution/exponential_family.py` — entropy
computed from natural parameters with autodiff of `_log_normalizer`. On TPU
this is a one-liner with `jax.grad` instead of the reference's dygraph
backward pass.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from .distribution import Distribution


class ExponentialFamily(Distribution):
    """Subclasses define `_natural_parameters` (tuple of Tensors),
    `_log_normalizer(*nat)` (pure jnp) and `_mean_carrier_measure`."""

    _mean_carrier_measure = 0.0

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_parameters):
        raise NotImplementedError

    def entropy(self):
        nat = self._natural_parameters

        def ent(*nvals):
            flat = [jnp.asarray(n, jnp.float32) for n in nvals]

            def lognorm_sum(*ns):
                return jnp.sum(self._log_normalizer(*ns))

            g = jax.grad(lognorm_sum, argnums=tuple(range(len(flat))))(*flat)
            result = self._log_normalizer(*flat) - self._mean_carrier_measure
            for n, gn in zip(flat, g):
                result = result - n * gn
            return result

        return apply("dist.ef_entropy", ent, *nat)
