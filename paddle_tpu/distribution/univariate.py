"""Univariate distributions.

Role parity: `python/paddle/distribution/{normal,uniform,bernoulli,beta,
binomial,cauchy,continuous_bernoulli,exponential,gamma,geometric,gumbel,
laplace,lognormal,poisson,student_t}.py`. Kernels are pure jnp (jax.scipy
special functions); reparameterized sampling where the pathwise gradient
exists (normal/uniform/gumbel/laplace/cauchy/exponential/gamma/beta use
base-noise transforms or jax's implicit-gradient gamma sampler).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.dispatch import apply
from .distribution import Distribution, _param
from .exponential_family import ExponentialFamily

_EULER = 0.5772156649015329
_LOG_SQRT_2PI = 0.5 * math.log(2 * math.pi)


def _bshape(*vals):
    return jnp.broadcast_shapes(*(jnp.shape(v) for v in vals))


class Normal(ExponentialFamily):
    """N(loc, scale). Ref: python/paddle/distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc._value, self.scale._value))

    @property
    def mean(self):
        return apply("normal.mean", lambda l, s: jnp.broadcast_to(
            l, _bshape(l, s)), self.loc, self.scale)

    @property
    def variance(self):
        return apply("normal.var", lambda l, s: jnp.broadcast_to(
            s * s, _bshape(l, s)), self.loc, self.scale)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(l, s):
            eps = jax.random.normal(key, out_shape, jnp.result_type(float))
            return l + s * eps

        return apply("normal.rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -0.5 * z * z - jnp.log(s) - _LOG_SQRT_2PI

        return apply("normal.log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        def f(l, s):
            return jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), _bshape(l, s))

        return apply("normal.entropy", f, self.loc, self.scale)

    def cdf(self, value):
        def f(v, l, s):
            return 0.5 * (1 + jsp.erf((v - l) / (s * math.sqrt(2.0))))

        return apply("normal.cdf", f, value, self.loc, self.scale)

    def icdf(self, value):
        def f(v, l, s):
            return l + s * math.sqrt(2.0) * jsp.erfinv(2 * v - 1)

        return apply("normal.icdf", f, value, self.loc, self.scale)

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    """U[low, high). Ref: python/paddle/distribution/uniform.py."""

    def __init__(self, low, high, name=None):
        self.low = _param(low)
        self.high = _param(high)
        super().__init__(_bshape(self.low._value, self.high._value))

    @property
    def mean(self):
        return apply("uniform.mean", lambda a, b: (a + b) / 2,
                     self.low, self.high)

    @property
    def variance(self):
        return apply("uniform.var", lambda a, b: (b - a) ** 2 / 12,
                     self.low, self.high)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(a, b):
            u = jax.random.uniform(key, out_shape, jnp.result_type(float))
            return a + (b - a) * u

        return apply("uniform.rsample", f, self.low, self.high)

    def log_prob(self, value):
        def f(v, a, b):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)

        return apply("uniform.log_prob", f, value, self.low, self.high)

    def entropy(self):
        return apply("uniform.entropy", lambda a, b: jnp.log(b - a),
                     self.low, self.high)

    def cdf(self, value):
        def f(v, a, b):
            return jnp.clip((v - a) / (b - a), 0.0, 1.0)

        return apply("uniform.cdf", f, value, self.low, self.high)


class Bernoulli(ExponentialFamily):
    """Bernoulli(probs). Ref: python/paddle/distribution/bernoulli.py."""

    def __init__(self, probs, name=None):
        self.probs = _param(probs)
        super().__init__(jnp.shape(self.probs._value))

    @property
    def logits(self):
        return apply("bernoulli.logits",
                     lambda p: jnp.log(p) - jnp.log1p(-p), self.probs)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return apply("bernoulli.var", lambda p: p * (1 - p), self.probs)

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            return jax.random.bernoulli(
                key, p, out_shape).astype(jnp.result_type(float))

        return apply("bernoulli.sample", f, self.probs).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            eps = jnp.finfo(jnp.result_type(float)).tiny
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply("bernoulli.log_prob", f, value, self.probs)

    def entropy(self):
        def f(p):
            return -(jsp.xlogy(p, p) + jsp.xlog1py(1 - p, -p))

        return apply("bernoulli.entropy", f, self.probs)

    def cdf(self, value):
        def f(v, p):
            return jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - p, 1.0))

        return apply("bernoulli.cdf", f, value, self.probs)


class ContinuousBernoulli(Distribution):
    """CB(lambda) of Loaiza-Ganem & Cunningham.
    Ref: python/paddle/distribution/continuous_bernoulli.py."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _param(probs)
        self._lims = lims
        super().__init__(jnp.shape(self.probs._value))

    def _norm_const(self, p):
        lo, hi = self._lims
        safe = jnp.where((p < lo) | (p > hi), p, 0.3)
        c = jnp.where(
            (p < lo) | (p > hi),
            (2 * jnp.arctanh(1 - 2 * safe)) / (1 - 2 * safe),
            2.0 + (p - 0.5) ** 2 * 8.0 / 3.0)
        return c

    @property
    def mean(self):
        def f(p):
            lo, hi = self._lims
            safe = jnp.where((p < lo) | (p > hi), p, 0.3)
            m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
            return jnp.where((p < lo) | (p > hi), m, 0.5)

        return apply("cb.mean", f, self.probs)

    @property
    def variance(self):
        def f(p):
            lo, hi = self._lims
            safe = jnp.where((p < lo) | (p > hi), p, 0.3)
            v = safe * (safe - 1) / (1 - 2 * safe) ** 2 + \
                1 / (2 * jnp.arctanh(1 - 2 * safe)) ** 2
            return jnp.where((p < lo) | (p > hi), v, 1 / 12.0)

        return apply("cb.var", f, self.probs)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            u = jax.random.uniform(key, out_shape, jnp.result_type(float),
                                   minval=1e-6, maxval=1 - 1e-6)
            lo, hi = self._lims
            safe = jnp.where((p < lo) | (p > hi), p, 0.3)
            x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                 / (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where((p < lo) | (p > hi), x, u)

        return apply("cb.rsample", f, self.probs)

    def log_prob(self, value):
        def f(v, p):
            eps = 1e-6
            pc = jnp.clip(p, eps, 1 - eps)
            return (jsp.xlogy(v, pc) + jsp.xlog1py(1 - v, -pc)
                    + jnp.log(self._norm_const(pc)))

        return apply("cb.log_prob", f, value, self.probs)


class Beta(ExponentialFamily):
    """Beta(alpha, beta). Ref: python/paddle/distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _param(alpha)
        self.beta = _param(beta)
        super().__init__(_bshape(self.alpha._value, self.beta._value))

    @property
    def mean(self):
        return apply("beta.mean", lambda a, b: a / (a + b),
                     self.alpha, self.beta)

    @property
    def variance(self):
        def f(a, b):
            s = a + b
            return a * b / (s * s * (s + 1))

        return apply("beta.var", f, self.alpha, self.beta)

    def rsample(self, shape=()):
        key = self._next_key()
        k1, k2 = jax.random.split(key)
        out_shape = self._extend_shape(shape)

        def f(a, b):
            ga = jax.random.gamma(k1, jnp.broadcast_to(a, out_shape))
            gb = jax.random.gamma(k2, jnp.broadcast_to(b, out_shape))
            return ga / (ga + gb)

        return apply("beta.rsample", f, self.alpha, self.beta)

    def log_prob(self, value):
        def f(v, a, b):
            return (jsp.xlogy(a - 1, v) + jsp.xlog1py(b - 1, -v)
                    - jsp.betaln(a, b))

        return apply("beta.log_prob", f, value, self.alpha, self.beta)

    def entropy(self):
        def f(a, b):
            s = a + b
            return (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                    - (b - 1) * jsp.digamma(b) + (s - 2) * jsp.digamma(s))

        return apply("beta.entropy", f, self.alpha, self.beta)


class Gamma(ExponentialFamily):
    """Gamma(concentration, rate). Ref: python/paddle/distribution/gamma.py."""

    def __init__(self, concentration, rate):
        self.concentration = _param(concentration)
        self.rate = _param(rate)
        super().__init__(
            _bshape(self.concentration._value, self.rate._value))

    @property
    def mean(self):
        return apply("gamma.mean", lambda c, r: c / r,
                     self.concentration, self.rate)

    @property
    def variance(self):
        return apply("gamma.var", lambda c, r: c / (r * r),
                     self.concentration, self.rate)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(c, r):
            g = jax.random.gamma(key, jnp.broadcast_to(c, out_shape))
            return g / r

        return apply("gamma.rsample", f, self.concentration, self.rate)

    def log_prob(self, value):
        def f(v, c, r):
            return (jsp.xlogy(c, r) + jsp.xlogy(c - 1, v) - r * v
                    - jsp.gammaln(c))

        return apply("gamma.log_prob", f, value, self.concentration, self.rate)

    # entropy comes from the ExponentialFamily Bregman identity — Gamma is
    # the subclass that exercises that path (natural params (c-1, -r),
    # log-normalizer gammaln(c) - c*log(r))
    @property
    def _natural_parameters(self):
        return (self.concentration - 1.0, -self.rate)

    def _log_normalizer(self, n1, n2):
        return jsp.gammaln(n1 + 1) - (n1 + 1) * jnp.log(-n2)


class Exponential(ExponentialFamily):
    """Exp(rate). Ref: python/paddle/distribution/exponential.py."""

    def __init__(self, rate):
        self.rate = _param(rate)
        super().__init__(jnp.shape(self.rate._value))

    @property
    def mean(self):
        return apply("exp.mean", lambda r: 1.0 / r, self.rate)

    @property
    def variance(self):
        return apply("exp.var", lambda r: 1.0 / (r * r), self.rate)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(r):
            e = jax.random.exponential(key, out_shape, jnp.result_type(float))
            return e / r

        return apply("exp.rsample", f, self.rate)

    def log_prob(self, value):
        def f(v, r):
            return jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf)

        return apply("exp.log_prob", f, value, self.rate)

    def entropy(self):
        return apply("exp.entropy", lambda r: 1.0 - jnp.log(r), self.rate)

    def cdf(self, value):
        def f(v, r):
            return jnp.where(v >= 0, 1 - jnp.exp(-r * v), 0.0)

        return apply("exp.cdf", f, value, self.rate)


class Laplace(Distribution):
    """Laplace(loc, scale). Ref: python/paddle/distribution/laplace.py."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc._value, self.scale._value))

    @property
    def mean(self):
        return apply("laplace.mean", lambda l, s: jnp.broadcast_to(
            l, _bshape(l, s)), self.loc, self.scale)

    @property
    def variance(self):
        return apply("laplace.var", lambda l, s: jnp.broadcast_to(
            2 * s * s, _bshape(l, s)), self.loc, self.scale)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(l, s):
            u = jax.random.uniform(key, out_shape, jnp.result_type(float),
                                   minval=-0.5 + 1e-7, maxval=0.5)
            return l - s * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))

        return apply("laplace.rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            return -jnp.abs(v - l) / s - jnp.log(2 * s)

        return apply("laplace.log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        def f(l, s):
            return jnp.broadcast_to(1 + jnp.log(2 * s), _bshape(l, s))

        return apply("laplace.entropy", f, self.loc, self.scale)

    def cdf(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

        return apply("laplace.cdf", f, value, self.loc, self.scale)

    def icdf(self, value):
        def f(v, l, s):
            t = v - 0.5
            return l - s * jnp.sign(t) * jnp.log1p(-2 * jnp.abs(t))

        return apply("laplace.icdf", f, value, self.loc, self.scale)


class Gumbel(Distribution):
    """Gumbel(loc, scale). Ref: python/paddle/distribution/gumbel.py."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc._value, self.scale._value))

    @property
    def mean(self):
        return apply("gumbel.mean", lambda l, s: l + s * _EULER,
                     self.loc, self.scale)

    @property
    def variance(self):
        return apply("gumbel.var",
                     lambda l, s: jnp.broadcast_to(
                         (math.pi ** 2 / 6.0) * s * s, _bshape(l, s)),
                     self.loc, self.scale)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(l, s):
            g = jax.random.gumbel(key, out_shape, jnp.result_type(float))
            return l + s * g

        return apply("gumbel.rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply("gumbel.log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        def f(l, s):
            return jnp.broadcast_to(jnp.log(s) + 1 + _EULER, _bshape(l, s))

        return apply("gumbel.entropy", f, self.loc, self.scale)

    def cdf(self, value):
        def f(v, l, s):
            return jnp.exp(-jnp.exp(-(v - l) / s))

        return apply("gumbel.cdf", f, value, self.loc, self.scale)


class Cauchy(Distribution):
    """Cauchy(loc, scale). Ref: python/paddle/distribution/cauchy.py."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc._value, self.scale._value))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(l, s):
            c = jax.random.cauchy(key, out_shape, jnp.result_type(float))
            return l + s * c

        return apply("cauchy.rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z * z))

        return apply("cauchy.log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        def f(l, s):
            return jnp.broadcast_to(
                jnp.log(4 * math.pi * s), _bshape(l, s))

        return apply("cauchy.entropy", f, self.loc, self.scale)

    def cdf(self, value):
        def f(v, l, s):
            return jnp.arctan((v - l) / s) / math.pi + 0.5

        return apply("cauchy.cdf", f, value, self.loc, self.scale)


class LogNormal(Distribution):
    """LogNormal(loc, scale) = exp(Normal).
    Ref: python/paddle/distribution/lognormal.py."""

    def __init__(self, loc, scale):
        self.loc = _param(loc)
        self.scale = _param(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(_bshape(self.loc._value, self.scale._value))

    @property
    def mean(self):
        return apply("lognormal.mean",
                     lambda l, s: jnp.exp(l + s * s / 2),
                     self.loc, self.scale)

    @property
    def variance(self):
        def f(l, s):
            s2 = s * s
            return jnp.expm1(s2) * jnp.exp(2 * l + s2)

        return apply("lognormal.var", f, self.loc, self.scale)

    def rsample(self, shape=()):
        base = self._base.rsample(shape)
        return apply("lognormal.exp", jnp.exp, base)

    def log_prob(self, value):
        def f(v, l, s):
            z = (jnp.log(v) - l) / s
            return -0.5 * z * z - jnp.log(s * v) - _LOG_SQRT_2PI

        return apply("lognormal.log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        def f(l, s):
            return jnp.broadcast_to(
                l + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                _bshape(l, s))

        return apply("lognormal.entropy", f, self.loc, self.scale)


class Poisson(ExponentialFamily):
    """Poisson(rate). Ref: python/paddle/distribution/poisson.py."""

    def __init__(self, rate):
        self.rate = _param(rate)
        super().__init__(jnp.shape(self.rate._value))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(r):
            return jax.random.poisson(
                key, r, out_shape).astype(jnp.result_type(float))

        return apply("poisson.sample", f, self.rate).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, r):
            return jsp.xlogy(v, r) - r - jsp.gammaln(v + 1)

        return apply("poisson.log_prob", f, value, self.rate)

    def entropy(self):
        # truncated series over a support window sized to the rate
        # (rate + 12*sqrt(rate) covers ~12 sigma; window must be static,
        # so it comes from the concrete rate — under tracing fall back to
        # a generous fixed bound)
        def f(r):
            try:
                hi = float(jnp.max(r))
                window = int(hi + 12.0 * math.sqrt(max(hi, 1.0))) + 16
            except (jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError):
                window = 1024
            n = jnp.arange(0.0, float(window))
            shape = jnp.shape(r)
            rr = jnp.reshape(r, (-1, 1))
            lp = jsp.xlogy(n, rr) - rr - jsp.gammaln(n + 1)
            ent = -jnp.sum(jnp.exp(lp) * lp, axis=-1)
            return jnp.reshape(ent, shape)

        return apply("poisson.entropy", f, self.rate)


class Geometric(Distribution):
    """Geometric(probs), support {0, 1, 2, ...}.
    Ref: python/paddle/distribution/geometric.py."""

    def __init__(self, probs):
        self.probs = _param(probs)
        super().__init__(jnp.shape(self.probs._value))

    @property
    def mean(self):
        return apply("geom.mean", lambda p: (1 - p) / p, self.probs)

    @property
    def variance(self):
        return apply("geom.var", lambda p: (1 - p) / (p * p), self.probs)

    @property
    def stddev(self):
        return apply("geom.std", lambda p: jnp.sqrt(1 - p) / p, self.probs)

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            u = jax.random.uniform(key, out_shape, jnp.result_type(float),
                                   minval=jnp.finfo(jnp.float32).tiny)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        return apply("geom.sample", f, self.probs).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            return jsp.xlog1py(v, -p) + jnp.log(p)

        return apply("geom.log_prob", f, value, self.probs)

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(jsp.xlogy(q, q) + jsp.xlogy(p, p)) / p

        return apply("geom.entropy", f, self.probs)

    def cdf(self, value):
        def f(v, p):
            return 1 - jnp.power(1 - p, jnp.floor(v) + 1)

        return apply("geom.cdf", f, value, self.probs)


class Binomial(Distribution):
    """Binomial(total_count, probs).
    Ref: python/paddle/distribution/binomial.py."""

    def __init__(self, total_count, probs):
        self.total_count = _param(total_count)
        self.probs = _param(probs)
        super().__init__(
            _bshape(self.total_count._value, self.probs._value))

    @property
    def mean(self):
        return apply("binom.mean", lambda n, p: n * p,
                     self.total_count, self.probs)

    @property
    def variance(self):
        return apply("binom.var", lambda n, p: n * p * (1 - p),
                     self.total_count, self.probs)

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(n, p):
            return jax.random.binomial(
                key, n.astype(jnp.float32), p,
                out_shape).astype(jnp.result_type(float))

        return apply("binom.sample", f, self.total_count, self.probs).detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, n, p):
            logc = (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1))
            return logc + jsp.xlogy(v, p) + jsp.xlog1py(n - v, -p)

        return apply("binom.log_prob", f, value, self.total_count, self.probs)

    def entropy(self):
        def f(n, p):
            nmax = int(jnp.max(n)) if jnp.ndim(n) else int(n)
            k = jnp.arange(0.0, nmax + 1.0)
            shape = _bshape(n, p)
            nn = jnp.reshape(jnp.broadcast_to(n, shape), (-1, 1))
            pp = jnp.reshape(jnp.broadcast_to(p, shape), (-1, 1))
            logc = (jsp.gammaln(nn + 1) - jsp.gammaln(k + 1)
                    - jsp.gammaln(nn - k + 1))
            lp = logc + jsp.xlogy(k, pp) + jsp.xlog1py(nn - k, -pp)
            lp = jnp.where(k <= nn, lp, -jnp.inf)
            ent = -jnp.sum(jnp.where(jnp.isfinite(lp), jnp.exp(lp) * lp, 0.0),
                           axis=-1)
            return jnp.reshape(ent, shape)

        return apply("binom.entropy", f, self.total_count, self.probs)


class StudentT(Distribution):
    """StudentT(df, loc, scale). Ref: python/paddle/distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _param(df)
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.df._value, self.loc._value,
                                 self.scale._value))

    @property
    def mean(self):
        def f(df, l, s):
            return jnp.where(df > 1, jnp.broadcast_to(l, _bshape(df, l, s)),
                             jnp.nan)

        return apply("t.mean", f, self.df, self.loc, self.scale)

    @property
    def variance(self):
        def f(df, l, s):
            v = jnp.where(df > 2, s * s * df / (df - 2), jnp.inf)
            return jnp.where(df > 1, v, jnp.nan)

        return apply("t.var", f, self.df, self.loc, self.scale)

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(df, l, s):
            t = jax.random.t(key, jnp.broadcast_to(df, out_shape),
                             dtype=jnp.result_type(float))
            return l + s * t

        return apply("t.rsample", f, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, df, l, s):
            z = (v - l) / s
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return apply("t.log_prob", f, value, self.df, self.loc, self.scale)

    def entropy(self):
        def f(df, l, s):
            h = ((df + 1) / 2 * (jsp.digamma((df + 1) / 2)
                                 - jsp.digamma(df / 2))
                 + 0.5 * jnp.log(df) + jsp.betaln(df / 2, 0.5) + jnp.log(s))
            return jnp.broadcast_to(h, _bshape(df, l, s))

        return apply("t.entropy", f, self.df, self.loc, self.scale)
