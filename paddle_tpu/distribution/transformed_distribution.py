"""TransformedDistribution: base distribution pushed through transforms.

Role parity: `python/paddle/distribution/transformed_distribution.py`.
Event-rank bookkeeping follows the compose rule: each transform consumes
`_event_rank` event dims and produces `_event_rank_out` (defaults equal),
and per-transform log-det terms are reduced over the event dims they do
not own before accumulating.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from .distribution import Distribution
from .transform import ChainTransform


def _ranks(t):
    in_r = t._event_rank
    return in_r, getattr(t, "_event_rank_out", in_r)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(transforms, (list, tuple)):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(shape)
        # forward event-rank accumulation from the base's event rank
        rank = len(base.event_shape)
        for t in self.transforms:
            in_r, out_r = _ranks(t)
            rank = max(rank, in_r) + (out_r - in_r)
        n = len(out_shape) - rank
        super().__init__(tuple(out_shape[:n]), tuple(out_shape[n:]))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x.detach()

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        event_rank = len(self.event_shape)
        y = value
        lp = None
        # walk transforms backwards; at each step the event rank transfers
        # from the transform's output side to its input side
        for t in reversed(self.transforms):
            in_r, out_r = _ranks(t)
            x = t.inverse(y)
            event_rank += in_r - out_r
            ldj = t.forward_log_det_jacobian(x)
            k = event_rank - in_r

            def reduce_ldj(l, k=k):
                if k > 0:
                    return jnp.sum(l, axis=tuple(range(-k, 0)))
                return l

            ldj_r = apply("td.reduce_ldj", reduce_ldj, ldj)
            lp = ldj_r if lp is None else apply(
                "td.add", jnp.add, lp, ldj_r)
            y = x
        base_lp = self.base.log_prob(y)
        k0 = event_rank - len(self.base.event_shape)
        if k0 > 0:
            base_lp = apply(
                "td.base_sum",
                lambda l: jnp.sum(l, axis=tuple(range(-k0, 0))), base_lp)
        if lp is None:
            return base_lp
        return apply("td.sub", jnp.subtract, base_lp, lp)
