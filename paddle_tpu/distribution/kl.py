"""KL divergence registry + closed forms.

Role parity: `python/paddle/distribution/kl.py` (`register_kl` decorator
dispatching on distribution types, `kl_divergence` entry).
"""
from __future__ import annotations


import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.dispatch import apply
from .independent import Independent
from .multivariate import Categorical, Dirichlet, MultivariateNormal
from .univariate import (
    Bernoulli, Beta, Exponential, Gamma, Geometric, Laplace, LogNormal,
    Normal, Uniform,
)

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    # most-derived match wins (reference resolves by type pair lookup with
    # mro walk)
    best, best_fn = None, None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            score = (type(p).__mro__.index(pc) + type(q).__mro__.index(qc))
            if best is None or score < best:
                best, best_fn = score, fn
    if best_fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return best_fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def f(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return apply("kl.normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def f(pa, pb, qa, qb):
        result = jnp.log((qb - qa) / (pb - pa))
        return jnp.where((qa <= pa) & (pb <= qb), result, jnp.inf)

    return apply("kl.uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def f(pp, qp):
        eps = jnp.finfo(jnp.float32).tiny
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))

    return apply("kl.bernoulli", f, p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return p.kl_divergence_categorical(q)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def f(pa, pb, qa, qb):
        ps, qs = pa + pb, qa + qb
        return (jsp.betaln(qa, qb) - jsp.betaln(pa, pb)
                + (pa - qa) * jsp.digamma(pa) + (pb - qb) * jsp.digamma(pb)
                + (qs - ps) * jsp.digamma(ps))

    return apply("kl.beta", f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def f(pc, qc):
        p0 = jnp.sum(pc, -1)
        return (jsp.gammaln(p0) - jnp.sum(jsp.gammaln(pc), -1)
                - jsp.gammaln(jnp.sum(qc, -1))
                + jnp.sum(jsp.gammaln(qc), -1)
                + jnp.sum((pc - qc) * (jsp.digamma(pc)
                                       - jsp.digamma(p0[..., None])), -1))

    return apply("kl.dirichlet", f, p.concentration, q.concentration)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def f(pc, pr, qc, qr):
        return ((pc - qc) * jsp.digamma(pc) - jsp.gammaln(pc)
                + jsp.gammaln(qc) + qc * (jnp.log(pr) - jnp.log(qr))
                + pc * (qr / pr - 1))

    return apply("kl.gamma", f, p.concentration, p.rate,
                 q.concentration, q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def f(pr, qr):
        ratio = qr / pr
        return ratio - 1 - jnp.log(ratio)

    return apply("kl.exponential", f, p.rate, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def f(pl, ps, ql, qs):
        adiff = jnp.abs(pl - ql)
        return (jnp.log(qs / ps) + adiff / qs
                + (ps / qs) * jnp.exp(-adiff / ps) - 1)

    return apply("kl.laplace", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    def f(pp, qp):
        return (-(1 - pp) / pp * (jnp.log1p(-qp) - jnp.log1p(-pp))
                + jnp.log(pp) - jnp.log(qp))

    return apply("kl.geometric", f, p.probs, q.probs)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    def f(pl, pL, ql, qL):
        import jax

        d = pl.shape[-1]
        half_logdet_p = jnp.sum(jnp.log(jnp.diagonal(
            pL, axis1=-2, axis2=-1)), -1)
        half_logdet_q = jnp.sum(jnp.log(jnp.diagonal(
            qL, axis1=-2, axis2=-1)), -1)
        # tr(Σq^-1 Σp) = ||Lq^-1 Lp||_F^2
        M = jax.scipy.linalg.solve_triangular(qL, pL, lower=True)
        tr = jnp.sum(M * M, axis=(-2, -1))
        diff = ql - pl
        sol = jax.scipy.linalg.solve_triangular(
            qL, diff[..., None], lower=True)[..., 0]
        mah = jnp.sum(sol * sol, -1)
        return 0.5 * (tr + mah - d) + half_logdet_q - half_logdet_p

    return apply("kl.mvn", f, p.loc, p.scale_tril, q.loc, q.scale_tril)


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError("mismatched reinterpreted_batch_rank")
    inner = kl_divergence(p.base, q.base)
    k = p.reinterpreted_batch_rank

    def f(v):
        return jnp.sum(v, axis=tuple(range(-k, 0)))

    return apply("kl.independent", f, inner)
