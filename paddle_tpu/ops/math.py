"""Elementwise + scalar math ops (paddle.tensor.math parity:
`python/paddle/tensor/math.py`, `ops.yaml` elementwise families)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core import dtypes as _dtypes

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "maximum", "minimum", "fmax", "fmin",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "abs", "sign", "floor", "ceil", "round", "trunc", "frac",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "erf", "erfinv", "sigmoid", "logit", "square", "reciprocal",
    "clip", "neg", "lerp", "angle", "conj", "real", "imag",
    "scale", "stanh", "softplus_op", "rad2deg", "deg2rad",
    "isnan", "isinf", "isfinite", "nan_to_num", "heaviside",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    "logaddexp", "logsumexp", "diff", "gcd", "lcm", "hypot", "copysign",
    "multiply_", "add_", "subtract_", "scale_", "clip_", "ldexp",
    "inner", "outer", "trapezoid", "increment", "nextafter",
    "digamma", "lgamma", "polygamma", "i0", "sgn",
]


def _bin(name, f):
    @op(name)
    def g(x, y, name=None):
        return f(x, y)

    g.__name__ = name
    return g


def _un(name, f):
    @op(name)
    def g(x, name=None):
        return f(x)

    g.__name__ = name
    return g


add = _bin("add", jnp.add)
subtract = _bin("subtract", jnp.subtract)
multiply = _bin("multiply", jnp.multiply)
divide = _bin("divide", jnp.true_divide)
floor_divide = _bin("floor_divide", jnp.floor_divide)
mod = _bin("mod", jnp.mod)
remainder = mod
maximum = _bin("maximum", jnp.maximum)
minimum = _bin("minimum", jnp.minimum)
fmax = _bin("fmax", jnp.fmax)
fmin = _bin("fmin", jnp.fmin)
atan2 = _bin("atan2", jnp.arctan2)
logaddexp = _bin("logaddexp", jnp.logaddexp)
hypot = _bin("hypot", jnp.hypot)
copysign = _bin("copysign", jnp.copysign)
nextafter = _bin("nextafter", jnp.nextafter)
heaviside = _bin("heaviside", jnp.heaviside)
gcd = _bin("gcd", jnp.gcd)
lcm = _bin("lcm", jnp.lcm)
def _ldexp_impl(x, y):
    # reference ldexp (python/paddle/tensor/math.py) computes x * 2**y and
    # documents y as "typically integers"; jnp.ldexp rejects float
    # exponents outright, so truncate-cast them (matching 2**int(y))
    if jnp.issubdtype(jnp.asarray(y).dtype, jnp.floating):
        y = jnp.trunc(y).astype(jnp.int32)
    return jnp.ldexp(x, y)


ldexp = _bin("ldexp", _ldexp_impl)

exp = _un("exp", jnp.exp)
expm1 = _un("expm1", jnp.expm1)
log = _un("log", jnp.log)
log2 = _un("log2", jnp.log2)
log10 = _un("log10", jnp.log10)
log1p = _un("log1p", jnp.log1p)
sqrt = _un("sqrt", jnp.sqrt)
rsqrt = _un("rsqrt", jax.lax.rsqrt)
abs = _un("abs", jnp.abs)
sign = _un("sign", jnp.sign)
sgn = sign
floor = _un("floor", jnp.floor)
ceil = _un("ceil", jnp.ceil)
round = _un("round", jnp.round)
trunc = _un("trunc", jnp.trunc)
frac = _un("frac", lambda v: v - jnp.trunc(v))
sin = _un("sin", jnp.sin)
cos = _un("cos", jnp.cos)
tan = _un("tan", jnp.tan)
asin = _un("asin", jnp.arcsin)
acos = _un("acos", jnp.arccos)
atan = _un("atan", jnp.arctan)
sinh = _un("sinh", jnp.sinh)
cosh = _un("cosh", jnp.cosh)
tanh = _un("tanh", jnp.tanh)
asinh = _un("asinh", jnp.arcsinh)
acosh = _un("acosh", jnp.arccosh)
atanh = _un("atanh", jnp.arctanh)
erf = _un("erf", jax.scipy.special.erf)
erfinv = _un("erfinv", jax.scipy.special.erfinv)
sigmoid = _un("sigmoid", jax.nn.sigmoid)
square = _un("square", jnp.square)
reciprocal = _un("reciprocal", jnp.reciprocal)
neg = _un("neg", jnp.negative)
angle = _un("angle", jnp.angle)
conj = _un("conj", jnp.conj)
real = _un("real", jnp.real)
imag = _un("imag", jnp.imag)
rad2deg = _un("rad2deg", jnp.rad2deg)
deg2rad = _un("deg2rad", jnp.deg2rad)
isnan = _un("isnan", jnp.isnan)
isinf = _un("isinf", jnp.isinf)
isfinite = _un("isfinite", jnp.isfinite)
digamma = _un("digamma", jax.scipy.special.digamma)
lgamma = _un("lgamma", jax.scipy.special.gammaln)
i0 = _un("i0", jnp.i0)


@op("pow")
def pow(x, y, name=None):
    return jnp.power(x, y)


float_power = _bin("float_power", jnp.float_power)


@op("logit")
def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@op("clip")
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


@op("lerp")
def lerp(x, y, weight, name=None):
    return x + weight * (y - x)


@op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if bias_after_scale:
        out = x * scale + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * scale
    return out


@op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@op("softplus")
def softplus_op(x, beta=1, threshold=20, name=None):
    # double-where keeps the untaken exp branch finite so its vjp can't
    # poison the gradient with inf*0=NaN (classic XLA where-grad trap)
    big = x * beta > threshold
    safe = jnp.where(big, jnp.zeros((), x.dtype), x)
    return jnp.where(big, x, jnp.log1p(jnp.exp(beta * safe)) / beta)


@op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@op("cumsum")
def cumsum(x, axis=None, dtype=None, name=None):
    dtype = _dtypes.convert_dtype(dtype)
    if axis is None:
        return jnp.cumsum(x.reshape(-1), dtype=dtype)
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@op("cumprod")
def cumprod(x, dim=None, dtype=None, name=None):
    dtype = _dtypes.convert_dtype(dtype)
    return jnp.cumprod(x, axis=dim, dtype=dtype)


@op("cummax")
def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummax(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    eq = x == vals
    ind = jax.lax.cummax(jnp.where(eq, idx, -1), axis=axis)
    return vals, ind.astype(_dtypes.convert_dtype(dtype))


@op("cummin")
def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummin(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    eq = x == vals
    ind = jax.lax.cummax(jnp.where(eq, idx, -1), axis=axis)
    return vals, ind.astype(_dtypes.convert_dtype(dtype))


@op("logcumsumexp")
def logcumsumexp(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


@op("logsumexp")
def logsumexp(x, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


@op("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@op("inner")
def inner(x, y, name=None):
    return jnp.inner(x, y)


@op("outer")
def outer(x, y, name=None):
    return jnp.outer(x, y)


@op("trapezoid")
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if dx is None and x is None:
        dx = 1.0
    return jnp.trapezoid(y, x=x, dx=dx if dx is not None else 1.0, axis=axis)


@op("polygamma")
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)


def increment(x, value=1.0, name=None):
    return x._rebind(add(x, value))


# --- in-place variants (functional rebind) -----------------------------------

def add_(x, y, name=None):
    return x._rebind(add(x, y))


def subtract_(x, y, name=None):
    return x._rebind(subtract(x, y))


def multiply_(x, y, name=None):
    return x._rebind(multiply(x, y))


def scale_(x, scale_v=1.0, bias=0.0, bias_after_scale=True, name=None):
    return x._rebind(scale(x, scale_v, bias, bias_after_scale))


def clip_(x, min=None, max=None, name=None):
    return x._rebind(clip(x, min, max))
