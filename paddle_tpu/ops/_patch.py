"""Patch tensor methods + operators onto Tensor (parity with how the
reference monkey-patches `python/paddle/tensor/` functions onto the pybind
Tensor class)."""
from __future__ import annotations


from ..core.tensor import Tensor
from . import creation, extra, linalg, logic, manipulation, math, reduction

# Ops with a reference-parity in-place variant (`<name>_`): the PHI yaml
# `inplace:` entries that map to public tensor API. Generated as
# compute-then-rebind — on TPU "in-place" is a handle rebind; XLA's buffer
# donation provides the actual memory reuse under jit.
INPLACE_BASES = (
    "abs acos acosh add addmm asin asinh atan atanh bitwise_and bitwise_not "
    "bitwise_or bitwise_xor cast ceil clip cos cosh cumprod cumsum digamma "
    "divide equal erfinv exp fill fill_diagonal floor floor_divide floor_mod "
    "frac gammaln gcd greater_equal greater_than hypot i0 index_add "
    "index_fill index_put lcm ldexp lerp less_equal less_than lgamma log "
    "log10 log1p log2 logical_and logical_not logical_or logical_xor logit "
    "masked_fill masked_scatter mod multigammaln multiply nan_to_num neg "
    "not_equal polygamma pow put_along_axis reciprocal remainder renorm "
    "round rsqrt scale sigmoid sin sinh sqrt squeeze subtract t tan tanh "
    "transpose tril triu trunc unsqueeze"
).split()


def _swap(f):
    def g(self, other, *a, **kw):
        return f(other, self, *a, **kw)

    return g


def patch_tensor():
    modules = (math, reduction, manipulation, linalg, logic, creation, extra)
    # Plain method names: tensor.method(...) == ops.method(tensor, ...)
    skip = {
        "to_tensor", "as_tensor", "zeros", "ones", "full", "empty", "arange",
        "linspace", "logspace", "eye", "rand", "randn", "randint", "randperm",
        "uniform", "normal", "standard_normal", "meshgrid", "create_parameter",
        "shape_op",
    }
    for mod in modules:
        for name in getattr(mod, "__all__", []):
            if name in skip or hasattr(Tensor, name):
                continue
            setattr(Tensor, name, getattr(mod, name))

    # remaining reference tensor_method_func names backed by other
    # namespaces (signal/linalg) or free functions
    from .. import signal as _signal
    from . import linalg as _linalg_mod

    if not hasattr(Tensor, "stft"):
        Tensor.stft = _signal.stft
    if not hasattr(Tensor, "istft"):
        Tensor.istft = _signal.istft
    if not hasattr(Tensor, "cond") and hasattr(_linalg_mod, "cond_number"):
        Tensor.cond = _linalg_mod.cond_number
    if not hasattr(Tensor, "unfold"):
        def _t_unfold(self, axis, size, step, name=None):
            import paddle_tpu as _P

            return _P.unfold(self, axis, size, step)

        Tensor.unfold = _t_unfold
    if not hasattr(Tensor, "is_tensor"):
        Tensor.is_tensor = lambda self: True
    if not hasattr(Tensor, "add_n"):
        def _t_add_n(self, inputs=None, name=None):
            from . import add_n as _add_n

            return _add_n([self] + list(inputs or []))

        Tensor.add_n = _t_add_n

    # Paddle-style aliases
    Tensor.mm = linalg.matmul
    Tensor.pow = math.pow
    Tensor.abs = math.abs

    # Operators
    Tensor.__add__ = math.add
    Tensor.__radd__ = _swap(math.add)
    Tensor.__sub__ = math.subtract
    Tensor.__rsub__ = _swap(math.subtract)
    Tensor.__mul__ = math.multiply
    Tensor.__rmul__ = _swap(math.multiply)
    Tensor.__truediv__ = math.divide
    Tensor.__rtruediv__ = _swap(math.divide)
    Tensor.__floordiv__ = math.floor_divide
    Tensor.__rfloordiv__ = _swap(math.floor_divide)
    Tensor.__mod__ = math.mod
    Tensor.__rmod__ = _swap(math.mod)
    Tensor.__pow__ = math.pow
    Tensor.__rpow__ = _swap(math.pow)
    Tensor.__matmul__ = linalg.matmul
    Tensor.__rmatmul__ = _swap(linalg.matmul)
    Tensor.__neg__ = math.neg
    Tensor.__abs__ = math.abs
    Tensor.__invert__ = logic.logical_not
    Tensor.__and__ = logic.bitwise_and
    Tensor.__or__ = logic.bitwise_or
    Tensor.__xor__ = logic.bitwise_xor
    Tensor.__lshift__ = logic.bitwise_left_shift
    Tensor.__rshift__ = logic.bitwise_right_shift
    Tensor.__eq__ = logic.equal
    Tensor.__ne__ = logic.not_equal
    Tensor.__lt__ = logic.less_than
    Tensor.__le__ = logic.less_equal
    Tensor.__gt__ = logic.greater_than
    Tensor.__ge__ = logic.greater_equal

    # In-place operator forms rebind the handle (paddle `x += y` semantics)
    def _iop(f):
        def g(self, other):
            return self._rebind(f(self, other))

        return g

    Tensor.__iadd__ = _iop(math.add)
    Tensor.__isub__ = _iop(math.subtract)
    Tensor.__imul__ = _iop(math.multiply)
    Tensor.__itruediv__ = _iop(math.divide)

    # Generated `<name>_` in-place variants: Tensor methods AND module-level
    # functions on paddle_tpu.ops (picked up by the package star-import)
    import sys

    ops_pkg = sys.modules.get("paddle_tpu.ops")

    def _inplace(f, nm):
        def g(self, *a, **kw):
            return self._rebind(f(self, *a, **kw))

        g.__name__ = nm
        g.__qualname__ = f"Tensor.{nm}"
        g.__doc__ = f"In-place variant of `{nm[:-1]}` (compute + rebind)."
        return g

    # where_ is special: the reference's inplace target is `x` (arg 2 of
    # where(condition, x, y)), not the receiver/condition
    def _where_(condition, x, y, name=None):
        out = Tensor.where(condition, x, y)
        return x._rebind(out) if isinstance(x, Tensor) else out

    _where_.__name__ = "where_"
    if not hasattr(Tensor, "where_"):
        Tensor.where_ = _where_
        if ops_pkg is not None and not hasattr(ops_pkg, "where_"):
            setattr(ops_pkg, "where_", _where_)

    for base in INPLACE_BASES:
        f = getattr(Tensor, base, None)
        if f is None or hasattr(Tensor, base + "_"):
            continue
        g = _inplace(f, base + "_")
        setattr(Tensor, base + "_", g)
        if ops_pkg is not None and not hasattr(ops_pkg, base + "_"):
            setattr(ops_pkg, base + "_", g)
