"""Patch tensor methods + operators onto Tensor (parity with how the
reference monkey-patches `python/paddle/tensor/` functions onto the pybind
Tensor class)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import creation, linalg, logic, manipulation, math, reduction


def _swap(f):
    def g(self, other, *a, **kw):
        return f(other, self, *a, **kw)

    return g


def patch_tensor():
    modules = (math, reduction, manipulation, linalg, logic, creation)
    # Plain method names: tensor.method(...) == ops.method(tensor, ...)
    skip = {
        "to_tensor", "as_tensor", "zeros", "ones", "full", "empty", "arange",
        "linspace", "logspace", "eye", "rand", "randn", "randint", "randperm",
        "uniform", "normal", "standard_normal", "meshgrid", "create_parameter",
        "shape_op",
    }
    for mod in modules:
        for name in getattr(mod, "__all__", []):
            if name in skip or hasattr(Tensor, name):
                continue
            setattr(Tensor, name, getattr(mod, name))

    # Paddle-style aliases
    Tensor.mm = linalg.matmul
    Tensor.pow = math.pow
    Tensor.abs = math.abs

    # Operators
    Tensor.__add__ = math.add
    Tensor.__radd__ = _swap(math.add)
    Tensor.__sub__ = math.subtract
    Tensor.__rsub__ = _swap(math.subtract)
    Tensor.__mul__ = math.multiply
    Tensor.__rmul__ = _swap(math.multiply)
    Tensor.__truediv__ = math.divide
    Tensor.__rtruediv__ = _swap(math.divide)
    Tensor.__floordiv__ = math.floor_divide
    Tensor.__rfloordiv__ = _swap(math.floor_divide)
    Tensor.__mod__ = math.mod
    Tensor.__rmod__ = _swap(math.mod)
    Tensor.__pow__ = math.pow
    Tensor.__rpow__ = _swap(math.pow)
    Tensor.__matmul__ = linalg.matmul
    Tensor.__rmatmul__ = _swap(linalg.matmul)
    Tensor.__neg__ = math.neg
    Tensor.__abs__ = math.abs
    Tensor.__invert__ = logic.logical_not
    Tensor.__and__ = logic.bitwise_and
    Tensor.__or__ = logic.bitwise_or
    Tensor.__xor__ = logic.bitwise_xor
    Tensor.__lshift__ = logic.bitwise_left_shift
    Tensor.__rshift__ = logic.bitwise_right_shift
    Tensor.__eq__ = logic.equal
    Tensor.__ne__ = logic.not_equal
    Tensor.__lt__ = logic.less_than
    Tensor.__le__ = logic.less_equal
    Tensor.__gt__ = logic.greater_than
    Tensor.__ge__ = logic.greater_equal

    # In-place operator forms rebind the handle (paddle `x += y` semantics)
    def _iop(f):
        def g(self, other):
            return self._rebind(f(self, other))

        return g

    Tensor.__iadd__ = _iop(math.add)
    Tensor.__isub__ = _iop(math.subtract)
    Tensor.__imul__ = _iop(math.multiply)
    Tensor.__itruediv__ = _iop(math.divide)
