"""The op layer: pure-jnp kernels behind the eager dispatch gate.

Role parity: `python/paddle/tensor/` + the YAML-generated C++ API
(`paddle/phi/api/yaml/ops.yaml`). Each op body is a pure function over jax
arrays — the same body serves eager execution, `jax.vjp` autograd, and
functional tracing under `jit.to_static`.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .extra import *  # noqa: F401,F403
from .logic import is_tensor  # noqa: F401

from . import _op_table  # noqa: F401  (generated surface — kept importable
# so a missing/broken regeneration breaks the build, not just the tests)
from ..core.dispatch import apply, op  # noqa: F401
from ..core.tensor import Tensor


def add_n(inputs, name=None):
    """Sum a list of tensors (paddle.add_n)."""
    import builtins

    if isinstance(inputs, Tensor):
        return inputs
    # NB: builtins.sum — this namespace shadows `sum` with the paddle op
    return apply("add_n", lambda *vs: builtins.sum(vs[1:], vs[0]), *inputs)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    def f(pred, lab):
        topk_idx = jnp.argsort(-pred, axis=-1)[:, :k]
        lab2 = lab.reshape(-1, 1)
        hit = jnp.any(topk_idx == lab2, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply("accuracy", f, input, label)


from ._patch import patch_tensor as _patch_tensor

_patch_tensor()
