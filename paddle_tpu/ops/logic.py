"""Comparison / logical / bitwise ops (paddle.tensor.logic parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "isin",
]


def _bin(name, f):
    @op(name)
    def g(x, y, name=None):
        return f(x, y)

    g.__name__ = name
    return g


equal = _bin("equal", jnp.equal)
not_equal = _bin("not_equal", jnp.not_equal)
greater_than = _bin("greater_than", jnp.greater)
greater_equal = _bin("greater_equal", jnp.greater_equal)
less_than = _bin("less_than", jnp.less)
less_equal = _bin("less_equal", jnp.less_equal)
logical_and = _bin("logical_and", jnp.logical_and)
logical_or = _bin("logical_or", jnp.logical_or)
logical_xor = _bin("logical_xor", jnp.logical_xor)
bitwise_and = _bin("bitwise_and", jnp.bitwise_and)
bitwise_or = _bin("bitwise_or", jnp.bitwise_or)
bitwise_xor = _bin("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _bin("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _bin("bitwise_right_shift", jnp.right_shift)


@op("logical_not")
def logical_not(x, name=None):
    return jnp.logical_not(x)


@op("bitwise_not")
def bitwise_not(x, name=None):
    return jnp.bitwise_not(x)


@op("equal_all")
def equal_all(x, y, name=None):
    return jnp.array_equal(x, y)


@op("allclose")
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@op("isclose")
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@op("is_empty")
def is_empty(x, name=None):
    return jnp.asarray(x.size == 0)


@op("isin")
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return jnp.isin(x, test_x, invert=invert)


def is_tensor(x):
    return isinstance(x, Tensor)
