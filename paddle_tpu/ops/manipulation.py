"""Shape/layout/indexing ops (paddle.tensor.manipulation parity:
`python/paddle/tensor/manipulation.py`)."""
from __future__ import annotations

_pyslice = slice  # the op below shadows the builtin

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, op
from ..core.tensor import Tensor
from ..core import dtypes as _dtypes

_I64 = _dtypes.convert_dtype("int64")  # int32 when x64 is off (TPU default)

__all__ = [
    "reshape", "reshape_", "transpose", "moveaxis", "swapaxes", "concat",
    "stack", "vstack", "hstack", "dstack", "split", "tensor_split", "chunk",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "flatten", "unflatten",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "tile",
    "flip", "rot90", "roll", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd", "scatter_nd_add", "index_select", "index_sample",
    "index_add", "index_put", "masked_select", "masked_fill", "masked_scatter",
    "where", "nonzero", "take", "take_along_axis", "put_along_axis",
    "one_hot", "topk", "sort", "argsort", "searchsorted", "bucketize",
    "unique", "unique_consecutive", "unbind", "cast", "getitem", "slice",
    "strided_slice", "crop", "pad", "repeat_interleave", "shard_index",
    "flatten_", "as_complex", "as_real", "view", "view_as", "atleast_1d",
    "atleast_2d", "atleast_3d", "tensordot", "numel", "rank", "shape_op",
    "tolist", "diagonal", "kron", "renorm", "trace",
]


@op("cast")
def cast(x, dtype):
    return x.astype(_dtypes.convert_dtype(dtype))


@op("reshape")
def reshape(x, shape, name=None):
    shape = [int(s) if not hasattr(s, "item") else int(s.item()) for s in shape] \
        if isinstance(shape, (list, tuple)) else shape
    return jnp.reshape(x, shape)


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape))


view = reshape


def view_as(x, other, name=None):
    return reshape(x, other.shape)


@op("transpose")
def transpose(x, perm=None, name=None):
    return jnp.transpose(x, perm)


@op("moveaxis")
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@op("swapaxes")
def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


@op("concat")
def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return jnp.concatenate(list(x), axis=int(axis))


@op("stack")
def stack(x, axis=0, name=None):
    return jnp.stack(list(x), axis=axis)


@op("vstack")
def vstack(x, name=None):
    return jnp.vstack(list(x))


@op("hstack")
def hstack(x, name=None):
    return jnp.hstack(list(x))


@op("dstack")
def dstack(x, name=None):
    return jnp.dstack(list(x))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} length {dim} is not divisible by "
                f"{num_or_sections}; pass explicit section sizes or use "
                f"tensor_split for uneven splits")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_neg = sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes)

    def f(v):
        return tuple(
            jax.lax.slice_in_dim(v, int(offsets[i]), int(offsets[i + 1]), axis=axis)
            for i in range(len(sizes))
        )

    return list(apply("split", f, x))


def tensor_split(x, num_or_indices, axis=0, name=None):
    dim = x.shape[int(axis)]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
    else:
        idxs = [0] + [int(i) for i in num_or_indices] + [dim]
        sizes = [idxs[i + 1] - idxs[i] for i in range(len(idxs) - 1)]
    return split(x, sizes, axis)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@op("squeeze")
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        if not axis:
            return x + 0 if jnp.issubdtype(x.dtype, jnp.inexact) else x
        return jnp.squeeze(x, axis=axis)
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


def squeeze_(x, axis=None, name=None):
    return x._rebind(squeeze(x, axis))


@op("unsqueeze")
def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist() if axis.ndim else int(axis.item())
    if isinstance(axis, (list, tuple)):
        out = x
        for a in axis:
            out = jnp.expand_dims(out, int(a))
        return out
    return jnp.expand_dims(x, int(axis))


def unsqueeze_(x, axis, name=None):
    return x._rebind(unsqueeze(x, axis))


@op("flatten")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s, e = start_axis % nd, stop_axis % nd
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, new_shape)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._rebind(flatten(x, start_axis, stop_axis))


@op("unflatten")
def unflatten(x, axis, shape, name=None):
    axis = axis % x.ndim
    return jnp.reshape(x, x.shape[:axis] + tuple(shape) + x.shape[axis + 1:])


@op("expand")
def expand(x, shape, name=None):
    shape = tuple(int(s) for s in shape)
    cur = (1,) * (len(shape) - x.ndim) + tuple(x.shape)
    tgt = tuple(c if s == -1 else s for s, c in zip(shape, cur))
    return jnp.broadcast_to(jnp.reshape(x, cur), tgt)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


@op("broadcast_to")
def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, tuple(int(s) for s in shape))


def broadcast_tensors(inputs, name=None):
    shape = np.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [broadcast_to(t, shape) for t in inputs]


@op("tile")
def tile(x, repeat_times, name=None):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@op("flip")
def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@op("rot90")
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@op("roll")
def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return jnp.roll(x, shifts, axis=axis)


@op("gather")
def gather(x, index, axis=0, name=None):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=int(axis))


@op("gather_nd")
def gather_nd(x, index, name=None):
    idx_last = index.shape[-1]
    flat_idx = index.reshape(-1, idx_last)
    out = x[tuple(flat_idx[:, i] for i in range(idx_last))]
    return out.reshape(index.shape[:-1] + x.shape[idx_last:])


@op("scatter")
def scatter(x, index, updates, overwrite=True, name=None):
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates, mode="drop")
    return x.at[index].add(updates, mode="drop")


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._rebind(scatter(x, index, updates, overwrite))


@op("scatter_nd_add")
def scatter_nd_add(x, index, updates, name=None):
    idx_last = index.shape[-1]
    flat_idx = index.reshape(-1, idx_last)
    flat_upd = updates.reshape((-1,) + x.shape[idx_last:])
    return x.at[tuple(flat_idx[:, i] for i in range(idx_last))].add(flat_upd)


def scatter_nd(index, updates, shape, name=None):
    from . import creation

    zeros = creation.zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(zeros, index, updates)


@op("index_select")
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, index, axis=int(axis))


@op("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index.astype(jnp.int32), axis=1)


@op("index_add")
def index_add(x, index, axis, value, name=None):
    axis = int(axis) % x.ndim
    moved = jnp.moveaxis(x, axis, 0)
    mv = jnp.moveaxis(value, axis, 0)
    out = moved.at[index].add(mv)
    return jnp.moveaxis(out, 0, axis)


@op("index_put")
def index_put(x, indices, value, accumulate=False, name=None):
    if accumulate:
        return x.at[indices].add(value)
    v = value
    if hasattr(v, "dtype") and v.dtype != x.dtype:
        v = v.astype(x.dtype)
    return x.at[indices].set(v)


@op("getitem")
def getitem(x, index):
    return x[index]


@op("masked_select")
def masked_select(x, mask, name=None):
    xb, mb = jnp.broadcast_arrays(x, mask)
    return xb[mb]


@op("masked_fill")
def masked_fill(x, mask, value, name=None):
    if hasattr(value, "dtype"):
        value = value.astype(x.dtype)
    return jnp.where(mask, value, x)


@op("masked_scatter")
def masked_scatter(x, mask, value, name=None):
    mask_b = jnp.broadcast_to(mask, x.shape)
    flat_m = mask_b.reshape(-1)
    flat_x = x.reshape(-1)
    flat_v = value.reshape(-1)
    pos = jnp.cumsum(flat_m) - 1
    src = flat_v[jnp.clip(pos, 0, flat_v.shape[0] - 1)]
    return jnp.where(flat_m, src, flat_x).reshape(x.shape)


@op("where")
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return tuple(o.astype(_I64) for o in jnp.nonzero(condition))
    return jnp.where(condition, x, y)


@op("nonzero")
def nonzero(x, as_tuple=False):
    outs = jnp.nonzero(x)
    if as_tuple:
        return tuple(o.astype(_I64)[:, None] for o in outs)
    return jnp.stack(outs, axis=1).astype(_I64)


@op("take")
def take(x, index, mode="raise", name=None):
    flat = x.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        idx = jnp.mod(index, n)
    elif mode == "clip":
        idx = jnp.clip(index, 0, n - 1)
    else:
        idx = jnp.where(index < 0, index + n, index)
    return flat[idx.reshape(-1)].reshape(index.shape)


@op("take_along_axis")
def take_along_axis(x, indices, axis, broadcast=True, name=None):
    return jnp.take_along_axis(x, indices, axis=axis)


@op("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    if not hasattr(values, "shape") or jnp.ndim(values) == 0:
        values = jnp.full(indices.shape, values, x.dtype)
    values = jnp.broadcast_to(values, indices.shape).astype(x.dtype)
    if reduce in ("add", "sum"):
        return _scatter_along_axis(x, indices, values, axis, "add")
    if reduce in ("mul", "multiply"):
        return _scatter_along_axis(x, indices, values, axis, "mul")
    return _scatter_along_axis(x, indices, values, axis, "set")


def _scatter_along_axis(x, indices, values, axis, mode):
    axis = axis % x.ndim
    idx_grids = jnp.meshgrid(
        *[jnp.arange(s) for s in indices.shape], indexing="ij")
    idx = list(idx_grids)
    idx[axis] = indices
    idx = tuple(idx)
    if mode == "add":
        return x.at[idx].add(values)
    if mode == "mul":
        return x.at[idx].multiply(values)
    return x.at[idx].set(values)


@op("one_hot")
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@op("topk")
def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, jax.Array):
        k = int(k)
    ax = -1 if axis is None else axis % x.ndim
    moved = jnp.moveaxis(x, ax, -1)
    if largest:
        vals, idxs = jax.lax.top_k(moved, k)
    else:
        vals, idxs = jax.lax.top_k(-moved, k)
        vals = -vals
    return (jnp.moveaxis(vals, -1, ax),
            jnp.moveaxis(idxs, -1, ax).astype(_I64))


@op("sort")
def sort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.sort(x, axis=axis, stable=stable or descending)
    return jnp.flip(out, axis=axis) if descending else out


@op("argsort")
def argsort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.argsort(x, axis=axis, stable=True)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out.astype(_I64)


@op("searchsorted")
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        flat_seq = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
        flat_val = values.reshape(-1, values.shape[-1])
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            flat_seq, flat_val).reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else _I64)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Host round-trip: output size is data-dependent (not jit-safe); the
    # reference's unique kernel is likewise dynamic-shape.
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = [Tensor(r.astype(np.int64) if i > 0 else r)
            for i, r in enumerate(res)]
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    import builtins

    # NB: this module defines a paddle `slice` op that shadows the builtin
    changed = np.ones(arr.shape[axis], dtype=bool)
    if arr.shape[axis] > 1:
        sl = [builtins.slice(None)] * arr.ndim
        sl_prev = list(sl)
        sl[axis] = builtins.slice(1, None)
        sl_prev[axis] = builtins.slice(None, -1)
        diffs = arr[tuple(sl)] != arr[tuple(sl_prev)]
        other_axes = tuple(i for i in range(arr.ndim) if i != axis)
        changed[1:] = diffs.any(axis=other_axes) if other_axes else diffs
    idx = np.nonzero(changed)[0]
    out = np.take(arr, idx, axis=axis)
    results = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(changed) - 1
        results.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        counts = np.diff(np.append(idx, arr.shape[axis]))
        results.append(Tensor(counts.astype(np.int64)))
    return results[0] if len(results) == 1 else tuple(results)


def unbind(x, axis=0, name=None):
    n = x.shape[int(axis)]

    def f(v):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(v, i, i + 1, axis=axis),
                                 axis=axis) for i in range(n))

    return list(apply("unbind", f, x))


@op("slice")
def slice(x, axes, starts, ends):
    sl = [_pyslice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        sl[ax] = _pyslice(int(s), int(e))
    return x[tuple(sl)]


@op("strided_slice")
def strided_slice(x, axes, starts, ends, strides, name=None):
    sl = [_pyslice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        sl[ax] = _pyslice(int(s), int(e), int(st))
    return x[tuple(sl)]


@op("crop")
def crop(x, shape=None, offsets=None, name=None):
    offsets = offsets or [0] * x.ndim
    shape = shape or x.shape
    sl = tuple(
        _pyslice(int(o), int(o) + (x.shape[i] - int(o) if int(s) == -1 else int(s)))
        for i, (o, s) in enumerate(zip(offsets, shape)))
    return x[sl]


@op("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    elif len(pad) == 4 and nd == 4:
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])]
        else:
            cfg = [(0, 0), (pad[2], pad[3]), (pad[0], pad[1]), (0, 0)]
    elif len(pad) == 6 and nd == 5:
        if data_format == "NCDHW":
            cfg = [(0, 0), (0, 0), (pad[4], pad[5]), (pad[2], pad[3]),
                   (pad[0], pad[1])]
        else:
            cfg = [(0, 0), (pad[4], pad[5]), (pad[2], pad[3]), (pad[0], pad[1]),
                   (0, 0)]
    elif len(pad) == 2 and nd == 3:
        if data_format == "NCL":
            cfg = [(0, 0), (0, 0), (pad[0], pad[1])]
        else:
            cfg = [(0, 0), (pad[0], pad[1]), (0, 0)]
    else:
        cfg = [(0, 0)] * (nd - len(pad) // 2) + \
              [(pad[2 * i], pad[2 * i + 1]) for i in range(len(pad) // 2)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode=jmode, constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@op("repeat_interleave")
def repeat_interleave(x, repeats, axis=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if hasattr(repeats, "shape") and jnp.ndim(repeats) > 0:
        total = int(jnp.sum(repeats))
        return jnp.repeat(x, repeats, axis=axis, total_repeat_length=total)
    return jnp.repeat(x, int(repeats), axis=axis)


@op("shard_index")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)


@op("as_complex")
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@op("as_real")
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op("atleast_1d")
def atleast_1d(x, name=None):
    return jnp.atleast_1d(x)


@op("atleast_2d")
def atleast_2d(x, name=None):
    return jnp.atleast_2d(x)


@op("atleast_3d")
def atleast_3d(x, name=None):
    return jnp.atleast_3d(x)


@op("tensordot")
def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@op("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@op("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op("kron")
def kron(x, y, name=None):
    return jnp.kron(x, y)


@op("renorm")
def renorm(x, p, axis, max_norm, name=None):
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def numel(x, name=None):
    return Tensor(np.int64(x.size))


def rank(x):
    return Tensor(np.int64(x.ndim))


def shape_op(x):
    return Tensor(np.asarray(x.shape, np.int64))


def tolist(x):
    return x.tolist()
