"""Tensor creation ops (paddle.tensor.creation parity:
`python/paddle/tensor/creation.py`)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtypes as _dtypes
from ..core import rng as _rng
from ..core.tensor import Parameter, Tensor

_I64 = _dtypes.convert_dtype("int64")  # int32 when x64 is off (TPU default)

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace",
    "logspace", "eye", "zeros_like", "ones_like", "full_like", "empty_like",
    "rand", "randn", "randint", "randperm", "uniform", "normal", "standard_normal",
    "bernoulli", "multinomial", "poisson", "assign", "clone", "tril_", "diag",
    "diagflat", "meshgrid", "tril", "triu", "create_parameter", "complex",
    "as_tensor",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    dtype = _dtypes.convert_dtype(dtype)
    if dtype is None:
        dtype = default or _dtypes.get_default_dtype()
    return dtype


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def as_tensor(data, dtype=None):
    if isinstance(data, Tensor) and (
        dtype is None or jnp.dtype(_dtypes.convert_dtype(dtype)) == data.dtype
    ):
        return data
    return Tensor(data, dtype=dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = v(start), v(end), v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(
            isinstance(x, (int, np.integer)) for x in (start, end, step)
        ) else _dtypes.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, _dt(dtype, "int64")))


def linspace(start, stop, num, dtype=None, name=None):
    def v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor(jnp.linspace(v(start), v(stop), int(v(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor(jnp.logspace(v(start), v(stop), int(v(num)), base=v(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def _like(x, dtype):
    dtype = _dtypes.convert_dtype(dtype) or x._value.dtype
    return tuple(x._value.shape), dtype


def zeros_like(x, dtype=None, name=None):
    shape, dt = _like(x, dtype)
    return Tensor(jnp.zeros(shape, dt))


def ones_like(x, dtype=None, name=None):
    shape, dt = _like(x, dtype)
    return Tensor(jnp.ones(shape, dt))


def full_like(x, fill_value, dtype=None, name=None):
    shape, dt = _like(x, dtype)
    return Tensor(jnp.full(shape, fill_value, dt))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def complex(real, imag, name=None):
    from ..core.dispatch import apply

    return apply("complex", jax.lax.complex, real, imag)


# --- random ------------------------------------------------------------------

def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    key = _rng.default_generator.split()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


standard_normal = randn


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = (_rng.default_generator.split() if not seed
           else jax.random.PRNGKey(seed))
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        key = _rng.default_generator.split()
        return Tensor(jax.random.normal(key, shp) * s + m)
    key = _rng.default_generator.split()
    shp = _shape(shape if shape is not None else [1])
    return Tensor(jax.random.normal(key, shp, _dt(None)) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = _rng.default_generator.split()
    return Tensor(jax.random.randint(key, _shape(shape), low, high,
                                     _dt(dtype, _I64)))


def randperm(n, dtype="int64", name=None):
    key = _rng.default_generator.split()
    return Tensor(jax.random.permutation(key, n).astype(_dt(dtype, _I64)))


def bernoulli(x, name=None):
    key = _rng.default_generator.split()
    from ..core.dispatch import apply

    return apply(
        "bernoulli",
        lambda v: jax.random.bernoulli(key, v).astype(v.dtype),
        x,
    )


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _rng.default_generator.split()
    v = x._value
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(*v.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(_I64))


def poisson(x, name=None):
    key = _rng.default_generator.split()
    return Tensor(jax.random.poisson(key, x._value).astype(x._value.dtype))


# --- misc --------------------------------------------------------------------

def assign(x, output=None):
    from ..core.dispatch import apply

    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    out = apply("assign", lambda v: v + 0, x)
    if output is not None:
        output._rebind(out)
        return output
    return out


def clone(x, name=None):
    return assign(x)


def diag(x, offset=0, padding_value=0, name=None):
    from ..core.dispatch import apply

    def f(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*out.shape, k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, v.dtype))
            return out
        return jnp.diagonal(v, offset=offset)

    return apply("diag", f, x)


def diagflat(x, offset=0, name=None):
    from ..core.dispatch import apply

    return apply("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def tril(x, diagonal=0, name=None):
    from ..core.dispatch import apply

    return apply("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    from ..core.dispatch import apply

    return apply("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_(x, diagonal=0, name=None):
    return x._rebind(tril(x, diagonal))


def meshgrid(*args, **kwargs):
    from ..core.dispatch import apply

    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply("meshgrid", lambda *vs: jnp.meshgrid(*vs, indexing="ij"), *args)
    return list(outs)


def create_parameter(shape, dtype=None, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    dtype = _dt(dtype)
    if default_initializer is not None:
        data = default_initializer(_shape(shape), dtype)
        if isinstance(data, Tensor):
            data = data._value
    elif is_bias:
        data = jnp.zeros(_shape(shape), dtype)
    else:
        key = _rng.default_generator.split()
        fan_in = _shape(shape)[0] if shape else 1
        bound = float(np.sqrt(6.0 / max(1, fan_in)))
        data = jax.random.uniform(key, _shape(shape), dtype, -bound, bound)
    return Parameter(data, name=name)
