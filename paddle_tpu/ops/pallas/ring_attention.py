"""Ring attention: context parallelism for sequences longer than one chip's
memory (SURVEY §5 long-context note: the reference has NO ring attention —
this is the capability-parity-plus point; its SEP axis only does Ulysses-
style alltoall).

Design: inside `shard_map` over the `sep` mesh axis, each device holds its
local Q/K/V sequence shard; K/V blocks rotate around the ring via
`lax.ppermute` while blockwise-softmax partial results fold in each visiting
block. The per-block attention is the Pallas flash kernel (flash_attention
._fwd/._bwd), so logits live in VMEM — local memory stays O(s_local·d), not
O(s_local²), which is what makes >HBM sequence lengths reachable. Forward
K/V rotate in the input dtype (bf16 on TPU), halving ICI bytes vs an f32
ring. Backward deliberately rotates the dK/dV running sums in f32 (2x the
forward ring's bytes): each hop would otherwise round the accumulator to
bf16, compounding error with ring size — the K/V blocks traveling alongside
still ride in bf16. Communication overlaps compute: each ppermute is issued
with the block math of the previous step still in flight (XLA schedules the
async collective-permute).

Differentiation is a custom VJP: forward saves (out, lse); backward runs a
second ring pass where each step computes the flash dQ/dK/dV for the block
currently held (three lax.switch branches: empty / causal-diagonal / full,
mirroring forward's block classification against the ring offset).

Blocks strictly above the causal diagonal never compute (empty branch), so
causal ring attention does ~half the flops, same as the single-chip kernel.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import flash_attention as FA

NEG_INF = -1e30


def _merge(acc, lse_acc, out_i, lse_i):
    """Fold one block's normalized partial (out_i, lse_i) into the running
    (acc f32 [b,sl,h,d], lse_acc f32 [b,h,sl,1]) via blockwise softmax."""
    new_lse = jnp.logaddexp(lse_acc, lse_i)
    # both operands can sit at the finite NEG_INF floor (fully masked row):
    # the subtraction stays finite, weights ~0.5 each, acc stays 0
    w_old = jnp.swapaxes(jnp.exp(lse_acc - new_lse), 1, 2)  # [b,sl,h,1]
    w_new = jnp.swapaxes(jnp.exp(lse_i - new_lse), 1, 2)
    acc = acc * w_old + out_i.astype(jnp.float32) * w_new
    return acc, new_lse


def _fwd_local(q, k, v, causal, block_q, block_k, axis_name):
    """Per-shard forward. q/k/v: [b, sl, h, d] locals. Returns (out, lse)."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape

    def flash_full(args):
        q_, k_, v_ = args
        return FA._fwd(q_, k_, v_, False, block_q, block_k)

    def flash_causal(args):
        q_, k_, v_ = args
        return FA._fwd(q_, k_, v_, True, block_q, block_k)

    def empty(args):
        q_, _, _ = args
        return (jnp.zeros_like(q_),
                jnp.full((b, h, sl, 1), NEG_INF, jnp.float32))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        acc, lse_acc, kc, vc = carry
        src = jnp.mod(my - i, n)  # origin shard of the kv block we hold
        if causal:
            # src > my: strictly above the diagonal — skip entirely
            branch = jnp.where(src == my, 1, jnp.where(src < my, 2, 0))
            out_i, lse_i = jax.lax.switch(
                branch, [empty, flash_causal, flash_full], (q, kc, vc))
        else:
            out_i, lse_i = flash_full((q, kc, vc))
        acc, lse_acc = _merge(acc, lse_acc, out_i, lse_i)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return acc, lse_acc, kc, vc

    acc0 = jnp.zeros((b, sl, h, d), jnp.float32)
    lse0 = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    acc, lse, _, _ = jax.lax.fori_loop(0, n, body, (acc0, lse0, k, v))
    return acc.astype(q.dtype), lse


def _bwd_local(q, k, v, out, lse, do, causal, block_q, block_k, axis_name):
    """Second ring pass: dK/dV accumulators travel WITH their kv blocks, so
    after the full cycle each lands back on its home shard."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)

    def bwd_full(args):
        q_, kc, vc = args
        return FA._bwd(q_, kc, vc, out, lse, do, False, block_q, block_k)

    def bwd_causal(args):
        q_, kc, vc = args
        return FA._bwd(q_, kc, vc, out, lse, do, True, block_q, block_k)

    def bwd_empty(args):
        q_, kc, vc = args
        return (jnp.zeros_like(q_), jnp.zeros_like(kc), jnp.zeros_like(vc))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        dq, kc, vc, dkc, dvc = carry
        src = jnp.mod(my - i, n)
        if causal:
            branch = jnp.where(src == my, 1, jnp.where(src < my, 2, 0))
            dq_i, dk_i, dv_i = jax.lax.switch(
                branch, [bwd_empty, bwd_causal, bwd_full], (q, kc, vc))
        else:
            dq_i, dk_i, dv_i = bwd_full((q, kc, vc))
        dq = dq + dq_i.astype(jnp.float32)
        dkc = dkc + dk_i.astype(jnp.float32)
        dvc = dvc + dv_i.astype(jnp.float32)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        return dq, kc, vc, dkc, dvc

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (dq0, k, v, jnp.zeros(k.shape, jnp.float32),
                     jnp.zeros(v.shape, jnp.float32)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_core(q, k, v, causal, block_q, block_k, axis_name):
    out, _ = _fwd_local(q, k, v, causal, block_q, block_k, axis_name)
    return out


def _ring_core_fwd(q, k, v, causal, block_q, block_k, axis_name):
    out, lse = _fwd_local(q, k, v, causal, block_q, block_k, axis_name)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(causal, block_q, block_k, axis_name, res, g):
    q, k, v, out, lse = res
    return _bwd_local(q, k, v, out, lse, g, causal, block_q, block_k,
                      axis_name)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


# ---------- jnp fallback body (shapes the kernel can't tile) ----------

def _local_ring_attention_jnp(q, k, v, *, axis_name, causal):
    """Materialized-logits fallback for shard shapes the flash kernel
    rejects (s_local % 8 != 0); O(s_local²) memory."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    qh = jnp.swapaxes(q, 1, 2)  # [b,h,sl,d]

    m0 = jnp.full((b, h, sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl), jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, acc, kc, vc = carry
        src = jnp.mod(my - i, n)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, jnp.swapaxes(kc, 1, 2),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my * sl + jax.lax.broadcasted_iota(
                jnp.int32, (sl, sl), 0)
            k_pos = src * sl + jax.lax.broadcasted_iota(
                jnp.int32, (sl, sl), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vc.dtype),
            jnp.swapaxes(vc, 1, 2), preferred_element_type=jnp.float32)
        kc_next = jax.lax.ppermute(kc, axis_name, perm)
        vc_next = jax.lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, kc_next, vc_next

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                   batch_axis="dp", head_axis="mp", block_q=None,
                   block_k=None, use_flash=None):
    """[B, S, H, D] global arrays (or tracers); S sharded over `seq_axis`.
    Falls back to a single-shard flash/ref path when the mesh has no seq
    axis. use_flash: None = platform policy (Pallas ring on real TPU, jnp
    body elsewhere), True/False = force (tests exercise the Pallas ring
    through the interpreter on CPU meshes with True)."""
    try:  # jax>=0.5 exports shard_map at top level
        from jax import shard_map
    except ImportError:  # jax 0.4.x: experimental namespace
        from jax.experimental.shard_map import shard_map

    from ...distributed import topology as topo_mod

    if mesh is None:
        mesh = topo_mod.current_spmd_mesh()
    if seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1:
        return FA.flash_attention_fwd(q, k, v, None, causal)

    h = q.shape[2]
    use_head = head_axis in mesh.shape and h % mesh.shape[head_axis] == 0
    use_batch = batch_axis in mesh.shape and \
        q.shape[0] % mesh.shape[batch_axis] == 0
    spec = P(batch_axis if use_batch else None, seq_axis,
             head_axis if use_head else None, None)

    sl = q.shape[1] // mesh.shape[seq_axis]
    d = q.shape[3]
    from ...core import flags

    # same policy as the single-chip flash gate: kill-switch flag honored,
    # Pallas only where it compiles (real TPU) — the interpreter would run
    # the kernels in Python per grid point; CPU meshes take the jnp body
    tileable = sl % 8 == 0 and d % 8 == 0 and d <= 256
    if use_flash is None:
        use_flash = flags.pallas_enabled("flash") and not FA._interpret()
    if use_flash and tileable:
        bq = FA._pick_block(sl, block_q or FA.DEFAULT_BLOCK_Q)
        bk = FA._pick_block(sl, block_k or FA.DEFAULT_BLOCK_K)

        def body(q_, k_, v_):
            # nondiff args positional: custom_vjp rejects keywords
            return _ring_core(q_, k_, v_, bool(causal), bq, bk, seq_axis)
    else:
        body = functools.partial(_local_ring_attention_jnp,
                                 axis_name=seq_axis, causal=causal)

    try:
        fn = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
    except TypeError:  # jax 0.4.x spells the replication check check_rep
        fn = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False)
    return fn(q, k, v)
