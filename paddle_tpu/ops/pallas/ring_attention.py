"""Ring attention: context parallelism for sequences longer than one chip's
memory (SURVEY §5 long-context note: the reference has NO ring attention —
this is the capability-parity-plus point; its SEP axis only does Ulysses-
style alltoall).

Design: inside `shard_map` over the `sep` mesh axis, each device holds its
local Q/K/V sequence shard; K/V blocks rotate around the ring via
`lax.ppermute` while an online-softmax accumulator (flash-attention style,
f32) folds in each block. Communication overlaps compute on ICI because each
ppermute is issued before the block math that uses the previous one is
consumed (XLA schedules the async collective-permute). Fully differentiable:
the VJP of ppermute is the reverse rotation, so backward is a ring too.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _local_ring_attention(q, k, v, *, axis_name, causal):
    """Per-shard body. q/k/v: [b, s_local, h, d]."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [b,h,sl,d]

    m0 = jnp.full((b, h, sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl), jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), jnp.float32)
    kc0 = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vc0 = jnp.swapaxes(v, 1, 2).astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, acc, kc, vc = carry
        src = jnp.mod(my - i, n)  # origin shard of the kv block we hold
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kc)
        if causal:
            q_pos = my * sl + jax.lax.broadcasted_iota(
                jnp.int32, (sl, sl), 0)
            k_pos = src * sl + jax.lax.broadcasted_iota(
                jnp.int32, (sl, sl), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vc)
        kc_next = jax.lax.ppermute(kc, axis_name, perm)
        vc_next = jax.lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, kc_next, vc_next

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, kc0, vc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                   batch_axis="dp", head_axis="mp"):
    """[B, S, H, D] global arrays (or tracers); S sharded over `seq_axis`.
    Falls back to a single-shard flash/ref path when the mesh has no seq
    axis."""
    from jax import shard_map

    from ...distributed import topology as topo_mod

    if mesh is None:
        mesh = topo_mod.current_spmd_mesh()
    if seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1:
        from .flash_attention import flash_attention_fwd

        return flash_attention_fwd(q, k, v, None, causal)

    h = q.shape[2]
    use_head = head_axis in mesh.shape and h % mesh.shape[head_axis] == 0
    use_batch = batch_axis in mesh.shape and \
        q.shape[0] % mesh.shape[batch_axis] == 0
    spec = P(batch_axis if use_batch else None, seq_axis,
             head_axis if use_head else None, None)

    fn = shard_map(
        functools.partial(_local_ring_attention, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
