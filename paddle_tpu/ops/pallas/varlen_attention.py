"""Varlen (packed / unpadded) flash attention — segment-masked kernels.

Role parity: `nn.functional.flash_attn_unpadded`
(python/paddle/nn/functional/flash_attention.py:302, backed by
third_party/flashattn's varlen CUDA kernels with cu_seqlens indexing).

TPU-first design: instead of the CUDA kernels' ragged cu_seqlens
indexing (data-dependent control flow XLA can't tile), the packed
[total, H, D] tensors run through the SAME blocked online-softmax /
backward loops as dense flash (`flash_attention._online_softmax`,
`_dq_loop`, `_dkv_loop`) with per-position SEGMENT IDS threaded into the
block masks: positions attend only within their segment, so the ragged
batch runs block-diagonal with static shapes and the T x T mask never
materializes. Segment ids ride as f32 [T, 1] columns (exact integer
equality far beyond any real batch size; f32 keeps the custom-VJP
cotangent plumbing trivial).

Layout: kernels consume head-major [H, T, D] (one transpose of the
packed tensors, same layout cost as the dense path); padded tail
positions (T padded to a multiple of 8) carry sentinel segment ids
(-1 on q, -2 on k) so they match nothing.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (
    _dkv_loop, _dq_loop, _interpret, _online_softmax, _pick_block,
)

__all__ = ["varlen_attention", "segment_ids_from_cu_seqlens"]


def segment_ids_from_cu_seqlens(cu_seqlens, total):
    """cu_seqlens [n+1] int (cu[0]=0, cu[n]=total) -> [total] segment
    ids (position t in [cu[i], cu[i+1]) gets id i)."""
    cu = jnp.asarray(cu_seqlens)
    t = jnp.arange(total, dtype=cu.dtype)
    return (jnp.searchsorted(cu, t, side="right") - 1).astype(jnp.int32)


def _dimsem():
    if _interpret():
        return None
    from .flash_attention import _ARB, _PLL, _TPUCompilerParams

    return _TPUCompilerParams(dimension_semantics=(_PLL, _ARB))


def _vl_fwd_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref, *,
                   scale, block_k, causal, seq_q, seq_k):
    block_q = q_ref.shape[0]
    out, lse = _online_softmax(
        q_ref[:],
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(1), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        seg_q=sq_ref[:],
        load_seg_k=lambda j: sk_ref[pl.ds(j * block_k, block_k), :])
    o_ref[:] = out.astype(o_ref.dtype)
    lse_ref[:] = lse.astype(jnp.float32)


def _vl_dq_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, sq_ref,
                  sk_ref, dq_ref, *, scale, block_k, causal, seq_q, seq_k):
    block_q = q_ref.shape[0]
    delta = jnp.sum(do_ref[:].astype(jnp.float32) *
                    o_ref[:].astype(jnp.float32), axis=1, keepdims=True)
    dq = _dq_loop(
        q_ref[:], do_ref[:], lse_ref[:], delta,
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(1), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        seg_q=sq_ref[:],
        load_seg_k=lambda j: sk_ref[pl.ds(j * block_k, block_k), :])
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _vl_dkv_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, sq_ref,
                   sk_ref, dk_ref, dv_ref, *, scale, block_q, causal,
                   seq_q, seq_k):
    block_k = k_ref.shape[0]
    dk, dv = _dkv_loop(
        k_ref[:], v_ref[:],
        lambda i: (q_ref[pl.ds(i * block_q, block_q), :],
                   do_ref[pl.ds(i * block_q, block_q), :],
                   o_ref[pl.ds(i * block_q, block_q), :],
                   lse_ref[pl.ds(i * block_q, block_q), :]),
        jk=pl.program_id(1), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        seg_k=sk_ref[:],
        load_seg_q=lambda i: sq_ref[pl.ds(i * block_q, block_q), :])
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _vl_fwd(qh, kh, vh, seg_q, seg_k, causal, block_q, block_k,
            seq_q_real, seq_k_real):
    """qh/kh/vh: [H, Tq|Tk, D] (padded); seg_*: [T*, 1] f32."""
    h, tq, d = qh.shape
    tk = kh.shape[1]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(tq, block_q)
    block_k = _pick_block(tk, block_k)
    out, lse = pl.pallas_call(
        functools.partial(_vl_fwd_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_q=seq_q_real,
                          seq_k=seq_k_real),
        grid=(h, pl.cdiv(tq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda hi, qi: (hi, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda hi, qi: (hi, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda hi, qi: (hi, 0, 0)),
            pl.BlockSpec((block_q, 1), lambda hi, qi: (qi, 0)),
            pl.BlockSpec((tk, 1), lambda hi, qi: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda hi, qi: (hi, qi, 0)),
            pl.BlockSpec((None, block_q, 1), lambda hi, qi: (hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, tq, d), qh.dtype),
            jax.ShapeDtypeStruct((h, tq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_dimsem(),
    )(qh, kh, vh, seg_q, seg_k)
    return out, lse


def _vl_bwd(qh, kh, vh, ot, lse, dot, seg_q, seg_k, causal, block_q,
            block_k, seq_q_real, seq_k_real):
    h, tq, d = qh.shape
    tk = kh.shape[1]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(tq, block_q)
    block_k = _pick_block(tk, block_k)

    q_spec = pl.BlockSpec((None, block_q, d), lambda hi, i: (hi, i, 0))
    full_q = pl.BlockSpec((None, tq, d), lambda hi, i: (hi, 0, 0))
    full_k = pl.BlockSpec((None, tk, d), lambda hi, i: (hi, 0, 0))
    lse_spec = pl.BlockSpec((None, block_q, 1), lambda hi, i: (hi, i, 0))
    full_lse = pl.BlockSpec((None, tq, 1), lambda hi, i: (hi, 0, 0))
    segq_blk = pl.BlockSpec((block_q, 1), lambda hi, i: (i, 0))
    segq_full = pl.BlockSpec((tq, 1), lambda hi, i: (0, 0))
    segk_full = pl.BlockSpec((tk, 1), lambda hi, i: (0, 0))
    segk_blk = pl.BlockSpec((block_k, 1), lambda hi, j: (j, 0))
    kv_spec = pl.BlockSpec((None, block_k, d), lambda hi, j: (hi, j, 0))

    dq = pl.pallas_call(
        functools.partial(_vl_dq_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_q=seq_q_real,
                          seq_k=seq_k_real),
        grid=(h, pl.cdiv(tq, block_q)),
        in_specs=[q_spec, full_k, full_k, q_spec, lse_spec, q_spec,
                  segq_blk, segk_full],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((h, tq, d), qh.dtype),
        interpret=_interpret(),
        compiler_params=_dimsem(),
    )(qh, kh, vh, ot, lse, dot, seg_q, seg_k)

    dk, dv = pl.pallas_call(
        functools.partial(_vl_dkv_kernel, scale=scale, block_q=block_q,
                          causal=causal, seq_q=seq_q_real,
                          seq_k=seq_k_real),
        grid=(h, pl.cdiv(tk, block_k)),
        in_specs=[full_q, kv_spec, kv_spec, full_q, full_lse, full_q,
                  segq_full, segk_blk],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((h, tk, d), kh.dtype),
                   jax.ShapeDtypeStruct((h, tk, d), vh.dtype)],
        interpret=_interpret(),
        compiler_params=_dimsem(),
    )(qh, kh, vh, ot, lse, dot, seg_q, seg_k)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _vl_core(qh, kh, vh, seg_q, seg_k, causal, block_q, block_k,
             seq_q_real, seq_k_real):
    out, _ = _vl_fwd(qh, kh, vh, seg_q, seg_k, causal, block_q, block_k,
                     seq_q_real, seq_k_real)
    return out


def _vl_core_fwd(qh, kh, vh, seg_q, seg_k, causal, block_q, block_k,
                 seq_q_real, seq_k_real):
    out, lse = _vl_fwd(qh, kh, vh, seg_q, seg_k, causal, block_q,
                       block_k, seq_q_real, seq_k_real)
    return out, (qh, kh, vh, out, lse, seg_q, seg_k)


def _vl_core_bwd(causal, block_q, block_k, seq_q_real, seq_k_real, res,
                 g):
    qh, kh, vh, out, lse, seg_q, seg_k = res
    dq, dk, dv = _vl_bwd(qh, kh, vh, out, lse, g, seg_q, seg_k, causal,
                         block_q, block_k, seq_q_real, seq_k_real)
    return dq, dk, dv, jnp.zeros_like(seg_q), jnp.zeros_like(seg_k)


_vl_core.defvjp(_vl_core_fwd, _vl_core_bwd)


def varlen_attention(q, k, v, cu_seqlens_q, cu_seqlens_k, scale=None,
                     causal=False, block_q=256, block_k=512):
    """Packed ragged-batch attention on raw jax values.

    q: [Tq, H, D]; k/v: [Tk, H, D]; cu_seqlens_*: [n+1] cumulative
    lengths. Returns [Tq, H, D]. Segment-masked Pallas kernels; with
    `causal`, cu_seqlens_q and cu_seqlens_k must describe the same
    packing (per-sequence causal needs aligned positions)."""
    tq, h, d = q.shape
    tk = k.shape[0]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    seg_q = segment_ids_from_cu_seqlens(cu_seqlens_q, tq)
    seg_k = segment_ids_from_cu_seqlens(cu_seqlens_k, tk)
    # fold an explicit scale into q so the kernels' 1/sqrt(d) nets out
    q = q * jnp.asarray(scale * math.sqrt(d), q.dtype)

    pad_q = (-tq) % 8
    pad_k = (-tk) % 8
    qh = jnp.swapaxes(jnp.pad(q, ((0, pad_q), (0, 0), (0, 0))), 0, 1)
    kh = jnp.swapaxes(jnp.pad(k, ((0, pad_k), (0, 0), (0, 0))), 0, 1)
    vh = jnp.swapaxes(jnp.pad(v, ((0, pad_k), (0, 0), (0, 0))), 0, 1)
    # sentinel segment ids on the padded tail: -1 (q) never equals -2 (k)
    sq = jnp.pad(seg_q.astype(jnp.float32), (0, pad_q),
                 constant_values=-1.0)[:, None]
    sk = jnp.pad(seg_k.astype(jnp.float32), (0, pad_k),
                 constant_values=-2.0)[:, None]
    out = _vl_core(qh, kh, vh, sq, sk, bool(causal), block_q, block_k,
                   tq, tk)
    return jnp.swapaxes(out, 0, 1)[:tq]
