"""Fused (residual+bias+)RMS/LayerNorm — Pallas TPU kernels.

Role parity: `paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu` and
`fused_rms_norm` (exposed as `incubate.nn.functional.fused_rms_norm` /
`fused_layer_norm` in the reference).

Design (TPU-first):
  * One VMEM pass per row-block: optional bias-add + residual-add, the
    norm statistics in f32, scale(+shift) — the pre-norm sum `z` is the
    second output (the transformer residual stream), so HBM sees exactly
    one read of (x, residual) and one write of (y, z).
  * Rows = all leading dims flattened; the feature axis stays whole in
    lanes (d multiple of 128 for the Pallas path; anything else falls
    back to the jnp body, which XLA fuses well for small d anyway).
  * Backward is recompute-style jnp (bandwidth-bound elementwise +
    row reductions that XLA emits as a single fused pass — see PERF.md
    for what has and hasn't been measured on hardware). The Pallas win
    is the forward, which sits on the decode / inference hot path and
    inside every transformer layer.
  * Non-TPU backends run the same kernel through the Pallas interpreter
    in tests (tests/test_pallas.py) to validate kernel code on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _interpret, _pick_block


def fused_norm_available(x, weight, bias) -> bool:
    from ...core import flags

    if not flags.pallas_enabled("fused_norm"):
        return False
    d = x.shape[-1]
    if d % 128 != 0 or d > 16384:
        return False
    if weight is not None and weight.shape != (d,):
        return False
    if bias is not None and bias.shape != (d,):
        return False
    return not _interpret()


def _row_block(rows, d):
    """Row-block size: big enough to amortize, small enough for VMEM
    (~2MB f32 working set), and dividing rows (full-array refs)."""
    pref = max(8, min(256, (2 << 20) // (4 * d)))
    return _pick_block(rows, pref)


# ============================ kernels ============================

def _norm_kernel(*refs, eps, kind, has_w, has_b, has_bias, has_res,
                 want_z):
    # refs order: x, [w], [b], [bias], [res], out, [z_out]
    i = 0
    x_ref = refs[i]; i += 1
    w_ref = refs[i] if has_w else None; i += has_w
    b_ref = refs[i] if has_b else None; i += has_b
    bias_ref = refs[i] if has_bias else None; i += has_bias
    res_ref = refs[i] if has_res else None; i += has_res
    o_ref = refs[i]; i += 1
    z_ref = refs[i] if want_z else None

    z = x_ref[:]
    if has_bias:
        z = z + bias_ref[:]
    if has_res:
        z = z + res_ref[:]
    if want_z:
        z_ref[:] = z.astype(z_ref.dtype)
    x32 = z.astype(jnp.float32)
    if kind == "rms":
        ms = jnp.mean(x32 * x32, axis=1, keepdims=True)
        y = x32 * jax.lax.rsqrt(ms + eps)
    else:
        mu = jnp.mean(x32, axis=1, keepdims=True)
        xc = x32 - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        y = xc * jax.lax.rsqrt(var + eps)
    if has_w:
        y = y * w_ref[:].astype(jnp.float32)
    if has_b:
        y = y + b_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _pallas_norm_fwd(x, w, b, bias, res, eps, kind, want_z,
                     interpret=None):
    shape = x.shape
    d = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    br = _row_block(rows, d)
    grid = (pl.cdiv(rows, br),)

    row_spec = pl.BlockSpec((br, d), lambda r: (r, 0))
    vec_spec = pl.BlockSpec((1, d), lambda r: (0, 0))

    operands, in_specs = [x2], [row_spec]
    if w is not None:
        operands.append(w.reshape(1, d)); in_specs.append(vec_spec)
    if b is not None:
        operands.append(b.reshape(1, d)); in_specs.append(vec_spec)
    if bias is not None:
        operands.append(bias.reshape(1, d)); in_specs.append(vec_spec)
    if res is not None:
        operands.append(res.reshape(rows, d)); in_specs.append(row_spec)

    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, d), x.dtype)]
    if want_z:
        out_specs.append(row_spec)
        out_shape.append(jax.ShapeDtypeStruct((rows, d), x.dtype))

    outs = pl.pallas_call(
        functools.partial(
            _norm_kernel, eps=eps, kind=kind, has_w=w is not None,
            has_b=b is not None, has_bias=bias is not None,
            has_res=res is not None, want_z=want_z),
        grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret() if interpret is None else interpret,
    )(*operands)
    if want_z:
        return outs[0].reshape(shape), outs[1].reshape(shape)
    return outs[0].reshape(shape), None


# ============================ vjp (jnp recompute) ============================

def _norm_bwd_math(z, w, gy, eps, kind):
    """dz, dw, db from upstream gy at pre-norm activation z."""
    z32 = z.astype(jnp.float32)
    g32 = gy.astype(jnp.float32)
    if kind == "rms":
        ms = jnp.mean(z32 * z32, axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(ms + eps)
        xhat = z32 * inv
    else:
        mu = jnp.mean(z32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(z32 - mu), axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(var + eps)
        xhat = (z32 - mu) * inv
    gw = g32 * w.astype(jnp.float32) if w is not None else g32
    if kind == "rms":
        dz = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    else:
        dz = inv * (gw - jnp.mean(gw, axis=-1, keepdims=True)
                    - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    rdims = tuple(range(z.ndim - 1))
    dw = jnp.sum(g32 * xhat, axis=rdims) if w is not None else None
    db = jnp.sum(g32, axis=rdims)
    return dz.astype(z.dtype), dw, db


_SPECIALIZATIONS = {}


def _build(kind, has_w, has_b, has_bias, has_res, eps):
    """Specialized custom-vjp fused-norm fn for one operand combination
    (custom_vjp needs a fixed positional signature — None args don't mix)."""
    key = (kind, has_w, has_b, has_bias, has_res, float(eps))
    fn = _SPECIALIZATIONS.get(key)
    if fn is not None:
        return fn
    want_z = has_bias or has_res

    def _unpack(args):
        it = iter(args)
        x = next(it)
        w = next(it) if has_w else None
        b = next(it) if has_b else None
        bias = next(it) if has_bias else None
        res = next(it) if has_res else None
        return x, w, b, bias, res

    @jax.custom_vjp
    def core(*args):
        x, w, b, bias, res = _unpack(args)
        y, z = _pallas_norm_fwd(x, w, b, bias, res, eps, kind, want_z)
        return (y, z) if want_z else y

    def core_fwd(*args):
        x, w, b, bias, res = _unpack(args)
        y, z = _pallas_norm_fwd(x, w, b, bias, res, eps, kind, want_z)
        # save the pre-norm activation (z when the op computes it, else x
        # itself) — backward recomputes the stats from it
        out = (y, z) if want_z else y
        return out, (z if want_z else x, w)

    def core_bwd(saved, g):
        z, w = saved
        gy = g[0] if want_z else g
        dz, dw, db = _norm_bwd_math(z, w, gy, eps, kind)
        if want_z:  # z is an output too: its cotangent adds straight in
            dz = dz + g[1].astype(dz.dtype)
        rdims = tuple(range(z.ndim - 1))
        grads = [dz]
        if has_w:
            grads.append(dw.astype(w.dtype))
        if has_b:
            grads.append(db.astype(z.dtype))
        if has_bias:
            grads.append(jnp.sum(dz.astype(jnp.float32),
                                 axis=rdims).astype(z.dtype))
        if has_res:
            grads.append(dz)
        return tuple(grads)

    core.defvjp(core_fwd, core_bwd)
    _SPECIALIZATIONS[key] = core
    return core


def fused_norm_pallas(x, w=None, b=None, bias=None, res=None,
                      eps=1e-6, kind="rms"):
    """Public fused-norm entry (jax arrays in/out).

    Returns `out` — or `(out, z)` with the pre-norm residual stream when
    `bias`/`res` participate (matching the reference fused op contract).
    """
    fn = _build(kind, w is not None, b is not None, bias is not None,
                res is not None, eps)
    args = [a for a in (x, w, b, bias, res) if a is not None]
    return fn(*args)
