"""Fused conv → norm scale/shift → activation — Pallas TPU kernel.

Role parity: the conv+BN+act fusions under
`paddle/phi/kernels/fusion/gpu/` (conv_bn fuse pass); here it is the
ISSUE-10 vision companion to the fused Swin window-attention kernel —
the ResNet/MobileNet stem+block pattern `relu(bn(conv(x)))` runs as ONE
kernel: the conv accumulates in f32, the folded batch-norm scale/shift
and the activation apply in VMEM, and the pre-activation conv output
never materializes in HBM.

Design (TPU-first):
  * The conv is expressed as kh*kw shifted MXU matmuls: for each kernel
    tap (dy, dx), a [C_out, C_in] weight slice contracts against the
    strided input window flattened to [C_in, rows*W_out]. No im2col
    buffer, no layout change — operands stay NCHW ([C, H, W] per batch,
    W in lanes), the layout the model tensors already carry.
  * Depthwise convs (groups == C_in == C_out, the MobileNet block) take
    a VPU elementwise path over the same shifted windows: the weight
    tap is [C, 1] and broadcasts down the flattened pixels.
  * Norm folding happens at the call site (`scale = gamma/sqrt(var+eps)`,
    `shift = beta - mean*scale + conv_bias*scale`): the kernel sees one
    affine — so the tier requires FROZEN norm stats (training-mode batch
    norm needs live batch stats; the dispatch gate routes it to the
    composed ops). AD still works: a custom VJP runs the fused kernel
    forward and differentiates the reference composed ops backward
    (frozen-BN fine-tuning, input-gradient probes).
  * Spatial padding is applied by the caller (`jnp.pad`, a cheap fused
    memset+copy) so every kernel tap is a static in-bounds slice.
  * The output-row band per grid cell is the autotuned parameter under
    the existing cache.
  * Non-TPU backends run the same kernel through the Pallas interpreter
    in tests; the eager CPU fallback is the jnp reference
    (`conv_bn_act_ref`, lax.conv + affine + act).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...observability import flight as _flight
from ...observability import metrics as _metrics
from .flash_attention import _interpret

__all__ = ["fused_conv_bn_act", "conv_bn_act_ref",
           "conv_bn_act_available"]

_VMEM_BOUND = 10 * 1024 * 1024

_ACTS = ("relu", "relu6", None)


def _apply_act(y, act):
    if act == "relu":
        return jnp.maximum(y, 0.0)
    if act == "relu6":
        return jnp.clip(y, 0.0, 6.0)
    return y


def conv_bn_act_ref(x, w, scale, shift, *, stride, padding, act,
                    depthwise=False):
    """jnp reference (the CPU dispatch fallback): lax.conv NCHW + folded
    affine + activation. x: [B, Cin, H, W]; w: [Cout, Cin/groups, kh, kw];
    scale/shift: [Cout]."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    out = jax.lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32), s,
        [(p[0], p[0]), (p[1], p[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=x.shape[1] if depthwise else 1)
    out = out * scale.astype(jnp.float32).reshape(1, -1, 1, 1) + \
        shift.astype(jnp.float32).reshape(1, -1, 1, 1)
    return _apply_act(out, act).astype(x.dtype)


# ========================= Pallas kernel =========================

def _conv_kernel(x_ref, w_ref, sc_ref, sh_ref, o_ref, *, kh, kw, sh_, sw_,
                 rows, w_out, act, depthwise):
    """x_ref: [Cin, rows_in, W_pad] (the full padded image — the row
    band selects its window with a provably-aligned dynamic offset);
    w_ref: [Cout, Cin_g, kh, kw]; sc/sh: [Cout, 1]; o_ref:
    [Cout, rows, W_out]."""
    cin = x_ref.shape[0]
    cout = o_ref.shape[0]
    r0 = pl.program_id(1) * (rows * sh_)    # static multiple per band
    acc = jnp.zeros((cout, rows * w_out), jnp.float32)
    for dy in range(kh):
        # rows dy, dy+s, ..., dy+(rows-1)*s of the padded input
        band = x_ref[:, pl.ds(r0 + dy, (rows - 1) * sh_ + 1), :]
        band = band[:, ::sh_, :]                    # [Cin, rows, W_pad]
        for dx in range(kw):
            win = band[:, :, dx:dx + (w_out - 1) * sw_ + 1:sw_]
            win = win.reshape(cin, rows * w_out).astype(jnp.float32)
            if depthwise:
                tap = w_ref[:, :, dy, dx].astype(jnp.float32)  # [C, 1]
                acc = acc + tap * win
            else:
                tap = w_ref[:, :, dy, dx].astype(jnp.float32)
                acc = acc + jax.lax.dot_general(
                    tap, win, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
    y = acc * sc_ref[:].astype(jnp.float32) + sh_ref[:].astype(
        jnp.float32)
    y = _apply_act(y, act)
    o_ref[:] = y.reshape(cout, rows, w_out).astype(o_ref.dtype)


def _out_dim(n, k, s, p):
    return (n + 2 * p - k) // s + 1


def _pick_rows(h_out, h_pad, cin, cin_g, cout, w_pad, w_out, kh, kw,
               itemsize):
    """Candidate output-row bands that divide H_out and fit the VMEM
    bound. The FULL padded image (cin*h_pad*w_pad) is resident in every
    cell regardless of band (the BlockSpec in `_conv_pallas` maps the
    whole image); the band only sizes the accumulator — for stride > 1
    sizing the input as the covered output rows would undercount by up
    to the stride factor and admit bands whose real cell exceeds the
    bound."""
    cands = []
    for r in (h_out, 56, 28, 16, 14, 8, 7, 4, 2, 1):
        if r <= h_out and h_out % r == 0 and r not in cands:
            # weight term uses cin_g ([C,1,kh,kw] for depthwise — a
            # cin-factor overestimate here rejected every band on the
            # exact MobileNet layers the VPU path targets)
            est = (cin * h_pad * w_pad * itemsize
                   + cout * cin_g * kh * kw * itemsize
                   + 2 * cout * r * w_out * 4)
            if est <= _VMEM_BOUND:
                cands.append(r)
    return cands


def conv_bn_act_available(x_shape, w_shape, stride, dilation, groups,
                          dtype_itemsize=4, training=False) -> bool:
    """Dispatch gate: TPU backend, pallas tier enabled, inference only
    (the scale/shift folding needs frozen norm stats), dense or
    depthwise conv, dilation 1, and a VMEM-feasible shape."""
    from ...core import flags

    if not flags.pallas_enabled("conv_norm"):
        return False
    if training:
        return False
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    b, cin, h, w = x_shape
    cout, cin_g, kh, kw = w_shape
    d = (dilation, dilation) if isinstance(dilation, int) else dilation
    if tuple(d) != (1, 1):
        return False
    depthwise = groups == cin and cout == cin and cin_g == 1
    if groups != 1 and not depthwise:
        return False
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if s[0] < 1 or s[1] < 1:
        return False
    # one full-image cell must fit even at the smallest band
    est = (cin * (h + kh) * (w + kw) * dtype_itemsize
           + cout * cin_g * kh * kw * dtype_itemsize
           + 2 * cout * w * 4)
    if est > _VMEM_BOUND:
        _metrics.inc("conv_norm.gate_reject", reason="vmem")
        _flight.record("conv_norm.gate_reject", reason="vmem",
                       x_shape=list(x_shape), w_shape=list(w_shape),
                       est_bytes=est)
        return False
    return not _interpret()


def _tuned_rows(x, w, stride, padding, act, depthwise, h_out, w_out,
                w_pad, cands):
    from . import autotune

    if len(cands) <= 1:
        return cands[0] if cands else h_out

    def run(rows):
        import numpy as np

        rs = np.random.RandomState(0)
        xv = jnp.asarray(rs.randn(*x.shape), x.dtype)
        wv = jnp.asarray(rs.randn(*w.shape), w.dtype)
        sc = jnp.ones((w.shape[0],), jnp.float32)
        sf = jnp.zeros((w.shape[0],), jnp.float32)

        def f(xv):
            # inference kernel: forward only; output is reshaped back to
            # the input's spatial shape only when shapes match (stride 1,
            # same padding) — otherwise chain via a resize-free trick:
            # time the kernel on a same-shaped dummy reduction feed
            y = fused_conv_bn_act(xv, wv, sc, sf, stride=stride,
                                  padding=padding, act=act,
                                  _rows_override=rows)
            # shape-preserving chain: fold the output back onto x's shape
            return jnp.broadcast_to(
                y.astype(xv.dtype).mean(), xv.shape) + xv * 0.5

        return f, xv

    sig = (f"{'x'.join(map(str, x.shape))}|{'x'.join(map(str, w.shape))}"
           f"|s{stride}|p{padding}|{'dw' if depthwise else 'g1'}"
           f"|{jnp.dtype(x.dtype).name}")
    return autotune.pick("conv_bn_act", sig, cands, run, cands[0])


def fused_conv_bn_act(x, w, scale, shift, *, stride=1, padding=0,
                      act="relu", _rows_override=None):
    """Public fused conv+norm+act entry (jax arrays in/out, NCHW).

    x: [B, Cin, H, W]; w: [Cout, Cin/groups, kh, kw] (groups inferred:
    dense when Cin_g == Cin, depthwise when Cin_g == 1 and Cout == Cin);
    scale/shift: [Cout] folded norm affine (conv bias pre-folded into
    shift by the caller). act: 'relu' | 'relu6' | None.

    Dispatch: Pallas on TPU when the gate admits the shape
    (`conv_norm.dispatch{tier=pallas}`), the lax.conv reference
    elsewhere (`tier=fallback`). Requires frozen norm stats (the affine
    is folded); differentiable — the custom VJP replays the reference
    composed ops backward."""
    assert act in _ACTS, act
    b, cin, h, w_in = x.shape
    cout, cin_g, kh, kw = w.shape
    depthwise = cin_g == 1 and cout == cin
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    groups = cin if depthwise else (cin // cin_g if cin_g else 1)
    if not conv_bn_act_available(x.shape, w.shape, s, 1, groups,
                                 jnp.dtype(x.dtype).itemsize):
        _metrics.inc("conv_norm.dispatch", tier="fallback")
        return conv_bn_act_ref(x, w, scale, shift, stride=s, padding=p,
                               act=act, depthwise=depthwise)
    _metrics.inc("conv_norm.dispatch", tier="pallas")
    h_out = _out_dim(h, kh, s[0], p[0])
    w_out = _out_dim(w_in, kw, s[1], p[1])
    h_pad = h + 2 * p[0]
    w_pad = w_in + 2 * p[1]
    cands = _pick_rows(h_out, h_pad, cin, cin_g, cout, w_pad, w_out,
                       kh, kw, jnp.dtype(x.dtype).itemsize)
    if _rows_override is not None:
        rows = _rows_override
    else:
        rows = _tuned_rows(x, w, s, p, act, depthwise, h_out, w_out,
                           w_pad, cands)
    return _conv_pallas_vjp((s, p, act, depthwise, rows),
                            x, w, scale, shift)


def _conv_pallas(x, w, scale, shift, s, p, act, depthwise, rows):
    """The Pallas invocation itself (tests call this directly — the
    interpreter runs the exact kernel code on CPU)."""
    b, cin, h, w_in = x.shape
    cout, cin_g, kh, kw = w.shape
    h_out = _out_dim(h, kh, s[0], p[0])
    w_out = _out_dim(w_in, kw, s[1], p[1])
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    h_pad, w_pad = xp.shape[2], xp.shape[3]
    grid = (b, h_out // rows)
    return pl.pallas_call(
        functools.partial(_conv_kernel, kh=kh, kw=kw, sh_=s[0], sw_=s[1],
                          rows=rows, w_out=w_out, act=act,
                          depthwise=depthwise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, cin, h_pad, w_pad),
                         lambda bi, ri: (bi, 0, 0, 0)),
            pl.BlockSpec((cout, cin_g, kh, kw),
                         lambda bi, ri: (0, 0, 0, 0)),
            pl.BlockSpec((cout, 1), lambda bi, ri: (0, 0)),
            pl.BlockSpec((cout, 1), lambda bi, ri: (0, 0)),
        ],
        out_specs=pl.BlockSpec((None, cout, rows, w_out),
                               lambda bi, ri: (bi, 0, ri, 0)),
        out_shape=jax.ShapeDtypeStruct((b, cout, h_out, w_out), x.dtype),
        interpret=_interpret(),
    )(xp, w, scale.reshape(cout, 1), shift.reshape(cout, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _conv_pallas_vjp(cfg, x, w, scale, shift):
    """Differentiable wrapper: fused Pallas forward, reference-composed
    backward. Without this, any AD through a fused-routed call (frozen-BN
    fine-tuning under jit, input-gradient probes) dies at trace time with
    'differentiation rule for pallas_call not implemented' — the eager
    grad gate in `vision/models/_fused.py` cannot see trace-mode AD.
    The backward replays `conv_bn_act_ref` (lax.conv + affine + act —
    the math the kernel matches exactly) and differentiates that, so
    gradients are the reference path's regardless of dispatch tier.
    cfg = (stride, padding, act, depthwise, rows), all static."""
    s, p, act, depthwise, rows = cfg
    return _conv_pallas(x, w, scale, shift, s, p, act, depthwise, rows)


def _conv_pallas_vjp_fwd(cfg, x, w, scale, shift):
    return _conv_pallas_vjp(cfg, x, w, scale, shift), (x, w, scale, shift)


def _conv_pallas_vjp_bwd(cfg, res, g):
    s, p, act, depthwise, _rows = cfg
    x, w, scale, shift = res
    _, vjp = jax.vjp(
        lambda xv, wv, sc, sh: conv_bn_act_ref(
            xv, wv, sc, sh, stride=s, padding=p, act=act,
            depthwise=depthwise),
        x, w, scale, shift)
    return vjp(g)


_conv_pallas_vjp.defvjp(_conv_pallas_vjp_fwd, _conv_pallas_vjp_bwd)
