"""Runtime block-size autotune for the Pallas kernel tier.

Role parity: `paddle/phi/kernels/autotune/` (`cache.h`,
`switch_autotune.cc`) — the reference times candidate kernel algorithms at
runtime and caches the winner per input signature. Here the "algorithm"
axis is Pallas block shape: on the first call for a given (op, shape,
dtype) signature on TPU, each candidate config is compiled and
slope-timed on the device with real data, and the winner is cached
in-process and on disk (so one process pays the search once per
signature, ever).

Gating: `FLAGS_use_autotune` (default on; `paddle.set_flags` or env).
Never runs in interpreter mode / off-TPU — the static default config is
used there.

Timing: value-fetch slope method (PERF.md "Measurement methodology") —
`block_until_ready` is unreliable through tunneled PJRT, so each
candidate is timed by chaining N iterations between two device-to-host
fetches and dividing the difference.
"""
from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

import jax

from ...observability import flight as _flight
from ...observability import metrics as _metrics
from ...observability import trace as _trace

_CACHE_PATH = os.environ.get(
    "PADDLE_TPU_AUTOTUNE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "autotune.json"))
_cache = None
_lock = threading.Lock()


def _enabled() -> bool:
    from ...core import flags

    return bool(flags.get_flags("FLAGS_use_autotune")["FLAGS_use_autotune"])


def _load() -> dict:  # pt-lint: ok[PT101,PT102] (callers hold _lock)
    global _cache
    if _cache is None:
        try:
            with open(_CACHE_PATH) as f:
                _cache = json.load(f)
        except Exception:
            _cache = {}
    return _cache


def _save() -> None:  # pt-lint: ok[PT102] (callers hold _lock)
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        tmp = _CACHE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_cache, f, indent=0, sort_keys=True)
        os.replace(tmp, _CACHE_PATH)
    except Exception as e:
        # cache is an optimization; never fail the op over it — but a
        # cache that silently stops persisting means every future
        # process re-pays the search (PERF.md r5: that is minutes)
        _flight.record("autotune.cache_write_failed", path=_CACHE_PATH,
                       error=f"{type(e).__name__}: {e}")


def _sync_fetch(r):
    leaf = jax.tree_util.tree_leaves(r)[0]
    return float(np.asarray(jax.device_get(leaf.ravel()[0:1]),
                            np.float32)[0])


def _slope_time(f, x, n1=2, n2=8) -> float:
    """Per-iteration seconds of shape-preserving `f` starting from `x`.

    The whole chain runs inside ONE jitted fori_loop with a traced trip
    count (round-5 methodology v2, PERF.md): chaining separate dispatches
    measures the tunnel's ~17 ms per-dispatch stall, not the kernel —
    r4's autotune picks at sub-10 ms kernel times were dispatch noise.
    One dispatch + one fetch per timing; the (d2-d1)/(n2-n1) difference
    cancels the constant."""
    @jax.jit
    def loop(x, n):
        return jax.lax.fori_loop(0, n, lambda i, y: f(y), x)

    _sync_fetch(loop(x, n1))  # compile + warm
    # a tunnel stall during either timing corrupts the difference —
    # clamping a negative diff to ~0 once made the WORST candidate "win"
    # a search (r5: (128,128) cached for 16x1024x12x64). Only positive
    # diffs count; a candidate with no valid timing in 4 tries loses.
    best = float("inf")
    valid = 0
    for _ in range(4):
        t0 = time.perf_counter()
        _sync_fetch(loop(x, n1))
        d1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync_fetch(loop(x, n2))
        d2 = time.perf_counter() - t0
        if d2 > d1:
            valid += 1
            best = min(best, (d2 - d1) / (n2 - n1))
            if valid >= 2:
                break
    if valid == 0:
        raise RuntimeError("no valid timing (tunnel stalls)")
    return best


def _devkind():
    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return None
        return getattr(dev, "device_kind", dev.platform)
    except Exception:
        return None


def cached_config(op: str, signature):
    """The cached winner for (device_kind, op, signature), else None.
    Pure lookup — never searches, never counts hit/miss (dispatch sites
    use it to detect deliberate non-reuse, e.g. the flash layout tag's
    cross-layout refusal)."""
    devkind = _devkind()
    if devkind is None:
        return None
    with _lock:
        hit = _load().get(f"{devkind}|{op}|{signature}")
    if hit is None:
        return None
    cfg = hit["config"]
    return tuple(cfg) if isinstance(cfg, list) else cfg


def pick(op: str, signature, candidates, run, default):
    """Return the fastest of `candidates` for this signature.

    run(config) must return ``(f, x)`` — a shape-preserving jax function
    executing the kernel with that config and its REAL device input — so
    timing can chain f inside one compiled loop (see _slope_time).
    Results are cached under (device_kind, op, signature). Falls back to
    `default` when autotune is disabled or every candidate fails.

    Telemetry: cache reuse counts `autotune.hit`, a fresh search counts
    `autotune.miss` (the search itself and its winner land in the flight
    recorder) — the counters that make a cold or poisoned cache visible
    instead of a silent 4x kernel slowdown (PERF.md r5).
    """
    if not _enabled() or len(candidates) <= 1:
        return default
    devkind = _devkind()
    if devkind is None:
        return default
    key = f"{devkind}|{op}|{signature}"
    with _lock:
        cache = _load()
        hit = cache.get(key)
    if hit is not None:
        _metrics.inc("autotune.hit")
        cfg = hit["config"]
        return tuple(cfg) if isinstance(cfg, list) else cfg
    _metrics.inc("autotune.miss")
    _flight.record("autotune.search", op=op, signature=str(signature),
                   n_candidates=len(candidates))
    # search outside the lock: candidate compiles can take seconds each.
    # The whole search is one trace span (it can cost seconds of bench
    # wall — it must be visible as a slice, not mystery idle time), with
    # the per-candidate timings attached once the winner is known.
    best, best_t, timings = None, float("inf"), {}
    with _trace.span(f"autotune.search:{op}", cat="autotune",
                     signature=str(signature),
                     n_candidates=len(candidates)) as _sp:
        for cfg in candidates:
            try:
                f, x = run(cfg)
                t = _slope_time(f, x)
            except Exception:
                # a config that fails to compile just loses — counted,
                # so "every candidate failed" is diagnosable from the
                # snapshot instead of looking like a silent default
                _metrics.inc("autotune.candidate_failed", op=op)
                continue
            timings[str(cfg)] = round(t * 1e3, 4)
            if t < best_t:
                best, best_t = cfg, t
        if _sp is not None:
            _sp.args["winner"] = str(best)
            _sp.args["ms"] = timings
    if best is None:
        _metrics.inc("autotune.search_failed")
        _flight.record("autotune.search_failed", op=op,
                       signature=str(signature), default=str(default))
        return default
    _flight.record("autotune.tuned", op=op, signature=str(signature),
                   winner=str(best), ms=timings)
    with _lock:
        cache = _load()
        cache[key] = {"config": list(best) if isinstance(best, tuple)
                      else best, "ms": timings}
        _save()
    return best


def clear_cache():
    """Drop the in-process and on-disk cache (tests / re-tuning)."""
    global _cache
    with _lock:
        _cache = {}
        try:
            os.remove(_CACHE_PATH)
        except OSError:
            pass
