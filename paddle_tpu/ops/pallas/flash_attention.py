"""Flash attention for TPU (Pallas).

Role parity: third_party/flashattn + `paddle/phi/kernels/fusion/gpu/` fused
attention kernels, exposed via `nn.functional.flash_attention`.

Round-1 state: the public entry points exist and route to a blockwise
reference implementation; the Pallas VMEM-blocked kernel lands in the fused
kernel milestone. The custom_vjp wiring is already in place so swapping the
kernel body does not change the API.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def flash_attention_available(q) -> bool:
    """Use the Pallas kernel when on TPU with supported shapes."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    if platform not in ("tpu",):
        return False
    d = q.shape[-1]
    return d in (64, 128, 256) and q.ndim == 4


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, mask, is_causal):
    return _flash_fwd_ref(q, k, v, mask, is_causal)[0]


def _flash_fwd_ref(q, k, v, mask, is_causal):
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    out = jnp.swapaxes(out, 1, 2).astype(q.dtype)
    return out, (q, k, v, mask, probs)


def _flash_bwd_ref(is_causal, res, g):
    q, k, v, mask, probs = res
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    gt = jnp.swapaxes(g, 1, 2).astype(jnp.float32)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", probs, gt)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gt, vt)
    ds = probs * (dp - jnp.sum(dp * probs, axis=-1, keepdims=True))
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kt) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qt) * scale
    dmask = None
    out = (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
           jnp.swapaxes(dk, 1, 2).astype(k.dtype),
           jnp.swapaxes(dv, 1, 2).astype(v.dtype),
           dmask)
    return out


def _fwd(q, k, v, mask, is_causal):
    out, res = _flash_fwd_ref(q, k, v, mask, is_causal)
    return out, res


def _bwd(is_causal, res, g):
    return _flash_bwd_ref(is_causal, res, g)


_flash.defvjp(_fwd, _bwd)


def flash_attention_fwd(q, k, v, mask=None, is_causal=False):
    """[B, S, H, D] in/out."""
    return _flash(q, k, v, mask, is_causal)
