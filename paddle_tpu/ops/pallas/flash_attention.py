"""Flash attention for TPU — Pallas VMEM-blocked kernel with custom VJP.

Role parity: third_party/flashattn + the fused attention kernels under
`paddle/phi/kernels/fusion/gpu/` (exposed as
`nn.functional.flash_attention`, flash_attention.py:146 in the reference).

Design (TPU-first, not a CUDA translation):
  * forward: grid (batch, heads, q_blocks); K/V live in VMEM per (b,h); an
    online-softmax fori_loop walks KV blocks with f32 running max/sum/acc —
    logits never materialize in HBM. Causal blocks that are fully masked are
    skipped by bounding the loop, and fully-unmasked blocks (strictly below
    the diagonal) take a mask-free body: the iota/compare/select chain only
    runs on diagonal blocks, which matters because the kernel is VPU-bound
    at head_dim 64 (PERF.md round-3 microbenchmarks).
  * dots run in the input dtype (bf16 on TPU) with f32 accumulation via
    preferred_element_type — casting operands to f32 first (round-2 design)
    forces the MXU off its bf16 path and measured 4x slower. The softmax
    scale is applied to the f32 logits, not the bf16 operands.
  * backward: recomputation-style — one kernel produces dQ (grid over
    q_blocks), one produces dK/dV (grid over kv_blocks), both replaying
    blocked logits from saved (out, logsumexp) rather than storing P; same
    bf16-dot + diagonal-only-masking treatment as forward.
  * block sizes are autotuned per signature on a fwd+bwd run (cached on
    disk; paddle/phi/kernels/autotune role). At B32 H12 S1024 D64 bf16 the
    tuned kernel measures ~4x over the 128x128 static default.
  * dtype: IO in input dtype, accumulation in f32; softmax stats rank-2
    `(block_q, 1)` f32 (rank-1 stats blocks do not lower to Mosaic).
  * non-TPU backends run the same kernels through the Pallas interpreter so
    CPU tests validate the exact kernel code (fake-backend strategy,
    SURVEY §4.5).

Supports is_causal; grad-free additive/boolean masks broadcastable to
[B, H, Sq, Sk] stream blockwise through the biased kernels (_flash_core_b),
trainable masks take the fused-softmax reference path.
"""
from __future__ import annotations

import contextlib
import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# telemetry (stdlib-only package — safe to import at kernel-module load).
# Dispatch decisions, gate rejects, and autotune reuse all happen at
# TRACE time, so the counters cost nothing per device step; the metrics
# registry itself is a no-op until observability.attach() enables it.
from ...observability import flight as _flight
from ...observability import metrics as _metrics

# jax-version compat: the deployed toolchain uses the modern pallas API
# (CompilerParams + GridDimensionSemantics enum); older jaxlib builds
# (0.4.x, the CPU CI image) spell them TPUCompilerParams + plain strings.
try:
    _PLL = pltpu.GridDimensionSemantics.PARALLEL
    _ARB = pltpu.GridDimensionSemantics.ARBITRARY
    _TPUCompilerParams = pltpu.CompilerParams
except AttributeError:
    _PLL, _ARB = "parallel", "arbitrary"
    _TPUCompilerParams = pltpu.TPUCompilerParams

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30

_DIMSEM = (_PLL, _PLL, _ARB)

# Flash layout default: "auto" — the transpose-free FLAT tier
# (everything on unpadded [B,S,H*D] views, zero relayouts — round-5
# kernels, gradients bit-identical to the transpose core) wherever the
# static lane/VMEM gates admit it, the transpose core everywhere else.
# Flipped from "transpose" after the round-5 parity tests + compile
# ladder proved flat correct and lowerable (docs/ATTENTION.md "The
# layout story"); tools/step_ab.py re-measures the full-step win each
# hardware window. Other tiers stay reachable via env
# FLAGS_flash_layout: "transpose" (per-head kernels over [B,H,S,D]
# with layout transposes around the call — the pre-flip default), "kv"
# (mixed: K/V/dK/dV stay native [B,S,H,D]), "flat" (force flat), "mh"
# (all-native all-heads blocks — rejected by the deployed server
# Mosaic, kept for newer toolchains).
_DEFAULT_LAYOUT = "auto"


_FORCE_COMPILED = False  # see force_tpu_lowering()


def _interpret():
    if _FORCE_COMPILED:
        return False
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _compiler_params():
    # dimension_semantics lets Mosaic reorder/parallelize the (b, h) grid
    # axes; the trailing q/kv-block axis stays sequential (online softmax /
    # accumulation carries). Interpreter mode rejects TPU compiler params.
    if _interpret():
        return None
    return _TPUCompilerParams(dimension_semantics=_DIMSEM)


@contextlib.contextmanager
def force_tpu_lowering():
    """Trace Pallas kernels for real Mosaic lowering even on a CPU host.

    Used by the TPU-lowering CI gate (tests/test_tpu_lowering.py): under
    `jax.export(..., platforms=['tpu'])` the kernels must go through
    `pallas_call(interpret=False)` so BlockSpec/Mosaic layout errors — the
    class of failure that broke the round-2 bench on hardware — surface
    without a chip."""
    global _FORCE_COMPILED
    old = _FORCE_COMPILED
    _FORCE_COMPILED = True
    try:
        yield
    finally:
        _FORCE_COMPILED = old


def flash_attention_available(q) -> bool:
    """Pallas path policy: TPU with MXU-friendly shapes. (CPU exercises the
    same kernels through the interpreter in tests/test_pallas.py; the eager
    CPU fallback is the jnp reference.)"""
    from ...core import flags

    if not flags.pallas_enabled("flash"):
        return False
    if q.ndim != 4:
        return False
    b, s, h, d = q.shape
    # odd sequence lengths (ViT's 197, ragged NLP batches) are handled by
    # padding to a multiple of 8 with real-length masking in the entry
    # point — only the head_dim constraints gate the kernel now
    if not (d % 8 == 0 and d <= 256):
        return False
    return not _interpret()


# =========================== forward kernel ===========================

def _online_softmax(q, load_kv, *, iq, block_q, block_k, scale, causal,
                    seq_q, seq_k, seg_q=None, load_seg_k=None,
                    load_bias=None):
    """The shared flash recurrence: walk KV blocks with f32 running
    max/sum/acc; logits never materialize in HBM. One body for BOTH
    forward kernels (per-head transpose layout and all-heads block) —
    the tests' bit-identical-forwards invariant rests on this being the
    single source of the numerics.

    q: [block_q, d] (input dtype; dots accumulate in f32 via
    preferred_element_type). load_kv(j) -> (k, v) each [block_k, d].
    Causal is bottom-right aligned like the reference (_ref_attention
    tril k=sk-sq): q row i attends k cols <= i + (seq_k - seq_q).
    Returns (out [block_q, d] f32, lse [block_q, 1] f32); stats are
    rank-2 — a rank-1 (block_q,) block does not lower to Mosaic
    (VERDICT r2 missing #2).

    seg_q/load_seg_k: varlen packed mode — segment ids ([block_q, 1] and
    per-block [block_k, 1]); positions attend only within their segment,
    so ragged batches run block-diagonal WITHOUT a T x T mask ever
    materializing (flash_attn_unpadded). Segment boundaries can cut any
    block, so every block runs the masked body in this mode.

    load_bias(j) -> [block_q, block_k] f32 additive bias (rel-pos /
    ALiBi / additive masks), added to the scaled logits before the
    running softmax — the bias streams blockwise, never a full [Sq, Sk]
    logits materialization.
    """
    d = q.shape[-1]
    off = seq_k - seq_q  # causal diagonal offset (0 for self-attention)
    num_k_blocks = pl.cdiv(seq_k, block_k)
    segmented = seg_q is not None

    def make_body(masked):
        def body(j, carry):
            m, l, acc = carry
            k, v = load_kv(j)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if load_bias is not None:
                s = s + load_bias(j)
            if masked:
                q_ids = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_ids = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                valid = k_ids < seq_k
                if causal:
                    valid = jnp.logical_and(valid, q_ids + off >= k_ids)
                if segmented:
                    seg_k = load_seg_k(j)  # [block_k, 1]
                    valid = jnp.logical_and(
                        valid, seg_q == seg_k.reshape(1, block_k))
                s = jnp.where(valid, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    carry0 = (jnp.full((block_q, 1), NEG_INF, jnp.float32),
              jnp.zeros((block_q, 1), jnp.float32),
              jnp.zeros((block_q, d), jnp.float32))
    if causal:
        # blocks with max k_id <= min q_id + off are fully unmasked:
        # mask-free body; the diagonal remainder runs the masked body.
        # (Segmented mode: boundaries cut anywhere, all blocks masked.)
        num_full = jnp.clip((iq * block_q + off + 1) // block_k,
                            0, num_k_blocks)
        num_iters = jnp.clip(pl.cdiv((iq + 1) * block_q + off, block_k),
                             num_full, num_k_blocks)
        if segmented:
            m, l, acc = jax.lax.fori_loop(0, num_iters, make_body(True),
                                          carry0)
        else:
            carry = jax.lax.fori_loop(0, num_full, make_body(False),
                                      carry0)
            m, l, acc = jax.lax.fori_loop(num_full, num_iters,
                                          make_body(True), carry)
    else:
        m, l, acc = jax.lax.fori_loop(
            0, num_k_blocks,
            make_body(segmented or seq_k % block_k != 0), carry0)
    l_safe = jnp.maximum(l, 1e-30)
    return acc / l_safe, m + jnp.log(l_safe)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                causal, seq_q, seq_k):
    # q_ref: [block_q, d]; k_ref/v_ref: [seq_k, d]; o_ref: [block_q, d];
    # lse_ref: [block_q, 1].
    block_q = q_ref.shape[0]
    out, lse = _online_softmax(
        q_ref[:],
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(2), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k)
    o_ref[:] = out.astype(o_ref.dtype)
    lse_ref[:] = lse.astype(jnp.float32)


def _fwd_kernel_bias(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref, *, scale,
                     block_k, causal, seq_q, seq_k):
    # b_ref: [block_q, seq_k] f32 additive bias row-band for this q block
    block_q = q_ref.shape[0]
    out, lse = _online_softmax(
        q_ref[:],
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(2), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        load_bias=lambda j: b_ref[:, pl.ds(j * block_k, block_k)]
        .astype(jnp.float32))
    o_ref[:] = out.astype(o_ref.dtype)
    lse_ref[:] = lse.astype(jnp.float32)


def _pick_block(seq, pref):
    """Largest multiple of 8 ≤ pref that divides seq (avoids OOB dynamic
    slices on the trailing block: refs are full-array, not pallas-padded).
    Loud on indivisible seq — a block that doesn't divide the sequence
    would read/write out of bounds and silently corrupt the tail rows
    (the dispatch gates route such shapes to the reference path; reaching
    here means _flash_core was called directly)."""
    if seq % 8 != 0:
        raise ValueError(
            f"flash attention Pallas kernel requires seq % 8 == 0, got "
            f"{seq}; use nn.functional attention entry points, which fall "
            "back to the fused-softmax reference path for such shapes")
    b = min(pref, seq)
    b -= b % 8
    while b > 8 and seq % b:
        b -= 8
    return max(b, 8)


def _fwd_t(qt, kt, vt, causal, block_q, block_k, seq_q_real=None,
           seq_k_real=None):
    """Forward on head-major [B,H,S,D] operands (the kernels' native
    layout). Returns (out_t [B,H,Sq,D], lse [B,H,Sq,1]).

    GQA: kt/vt may carry fewer heads ([B,Hkv,S,D], Hq % Hkv == 0) — the
    K/V index maps group query heads onto their KV head (hi // rep), so
    the shrunken KV is read directly instead of materializing a
    repeat_interleave'd copy (the reference expands; on TPU that
    multiplies KV HBM traffic by the group size for nothing).

    seq_q_real/seq_k_real: logical lengths when the arrays are padded to
    a block-friendly multiple (odd ViT-style lengths, e.g. 197): the
    kernels mask on the REAL bounds (k_ids < seq_k), padded key rows
    never contribute, and the caller slices padded q rows off the
    output."""
    b, h, sq, d = qt.shape
    h_kv = kt.shape[1]
    assert h % h_kv == 0, (h, h_kv)
    rep = h // h_kv
    sk = kt.shape[2]
    sq_r = seq_q_real or sq
    sk_r = seq_k_real or sk
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    grid = (b, h, pl.cdiv(sq, block_q))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq_r, seq_k=sk_r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, sk, d),
                         lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((None, None, sk, d),
                         lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt)
    return out, lse


def _fwd(q, k, v, causal, block_q, block_k):
    out, lse = _fwd_t(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                      jnp.swapaxes(v, 1, 2), causal, block_q, block_k)
    return jnp.swapaxes(out, 1, 2), lse


# ================== multi-head-block forward (no transposes) ==================

def _fwd_kernel_mh(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                   causal, seq_q, seq_k, n_heads):
    """All-heads-in-block variant: operates directly on [B,S,H,D] arrays.

    Mosaic cannot lower a squeezed-H block over [B,S,H,D] (the last two
    block dims must be divisible by (8,128) or EQUAL the array dims —
    a squeezed H=12 between S and D is neither), but a block carrying the
    FULL head dim is legal (equal-to-array-dim rule). The kernel then
    walks heads with static slices — a sublane extract per head, O(bq*d),
    negligible next to the O(bq*sk*d) dots — and the [B,S,H,D]<->[B,H,S,D]
    transposes around every attention call (~25 ms/step, PERF.md) never
    exist. VMEM holds K/V for ALL heads (seq_k*H*D*2*itemsize), so this
    path suits moderate S*H*D; the dispatcher keeps the transpose path
    for larger shapes.
    q_ref/o_ref: [block_q, H, D]; k_ref/v_ref: [seq_k, H, D];
    lse_ref: [H, block_q, 1].
    """
    block_q = q_ref.shape[0]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        out, lse = _online_softmax(
            q_ref[:, hh, :],
            lambda j, hh=hh: (k_ref[pl.ds(j * block_k, block_k), hh, :],
                              v_ref[pl.ds(j * block_k, block_k), hh, :]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        o_ref[:, hh, :] = out.astype(o_ref.dtype)
        lse_ref[hh, :, :] = lse.astype(jnp.float32)


def _fwd_mh(q, k, v, causal, block_q, block_k):
    """Forward on [B,S,H,D] with zero layout changes (see _fwd_kernel_mh).
    Returns (out [B,S,H,D], lse [B,H,Sq,1])."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    dimsem = None
    if not _interpret():
        dimsem = _TPUCompilerParams(
            dimension_semantics=(_PLL, _ARB))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_mh, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, h, d), lambda bi, qi: (bi, qi, 0, 0)),
            pl.BlockSpec((None, sk, h, d), lambda bi, qi: (bi, 0, 0, 0)),
            pl.BlockSpec((None, sk, h, d), lambda bi, qi: (bi, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, h, d), lambda bi, qi: (bi, qi, 0, 0)),
            pl.BlockSpec((None, h, block_q, 1), lambda bi, qi: (bi, 0, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=dimsem,
    )(q, k, v)
    return out, lse


# =========================== backward kernels ===========================

def _dq_loop(q, do, lse, delta, load_kv, *, iq, block_q, block_k, scale,
             causal, seq_q, seq_k, seg_q=None, load_seg_k=None,
             load_bias=None):
    """Shared dQ recurrence (replays blocked logits from lse; bf16 dots,
    f32 accumulation). One body for the per-head and all-heads-block dQ
    kernels. load_kv(j) -> (k, v). Returns dq [block_q, d] f32.
    seg_q/load_seg_k: varlen segment ids; load_bias: additive bias
    blocks (see _online_softmax) — the bias replays into the logits so
    p matches forward."""
    d = q.shape[-1]
    off = seq_k - seq_q
    num_k_blocks = pl.cdiv(seq_k, block_k)
    segmented = seg_q is not None

    def make_body(masked):
        def body(j, dq):
            k, v = load_kv(j)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if load_bias is not None:
                s = s + load_bias(j)
            if masked:
                q_ids = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_ids = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                valid = k_ids < seq_k
                if causal:
                    valid = jnp.logical_and(valid, q_ids + off >= k_ids)
                if segmented:
                    seg_k = load_seg_k(j)
                    valid = jnp.logical_and(
                        valid, seg_q == seg_k.reshape(1, block_k))
                s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta) * scale).astype(q.dtype)
            return dq + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return body

    dq0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        num_full = jnp.clip((iq * block_q + off + 1) // block_k,
                            0, num_k_blocks)
        num_iters = jnp.clip(pl.cdiv((iq + 1) * block_q + off, block_k),
                             num_full, num_k_blocks)
        if segmented:
            dq = jax.lax.fori_loop(0, num_iters, make_body(True), dq0)
        else:
            dq = jax.lax.fori_loop(0, num_full, make_body(False), dq0)
            dq = jax.lax.fori_loop(num_full, num_iters, make_body(True),
                                   dq)
    else:
        dq = jax.lax.fori_loop(0, num_k_blocks,
                               make_body(segmented or
                                         seq_k % block_k != 0), dq0)
    return dq


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dq_ref, *,
                   scale, block_k, causal, seq_q, seq_k):
    block_q = q_ref.shape[0]
    delta = jnp.sum(do_ref[:].astype(jnp.float32) *
                    o_ref[:].astype(jnp.float32), axis=1, keepdims=True)
    dq = _dq_loop(
        q_ref[:], do_ref[:], lse_ref[:], delta,
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(2), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _bwd_dq_kernel_bias(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                        do_ref, dq_ref, *, scale, block_k, causal, seq_q,
                        seq_k):
    block_q = q_ref.shape[0]
    delta = jnp.sum(do_ref[:].astype(jnp.float32) *
                    o_ref[:].astype(jnp.float32), axis=1, keepdims=True)
    dq = _dq_loop(
        q_ref[:], do_ref[:], lse_ref[:], delta,
        lambda j: (k_ref[pl.ds(j * block_k, block_k), :],
                   v_ref[pl.ds(j * block_k, block_k), :]),
        iq=pl.program_id(2), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        load_bias=lambda j: b_ref[:, pl.ds(j * block_k, block_k)]
        .astype(jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _bwd_dq_kernel_mh(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dq_ref,
                      *, scale, block_k, causal, seq_q, seq_k, n_heads):
    """All-heads-block dQ: [B,S,H,D] operands in place (see
    _fwd_kernel_mh). q/o/do/dq refs: [block_q, H, D]; k/v: [seq_k, H, D];
    lse: [H, block_q, 1]."""
    block_q = q_ref.shape[0]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        do = do_ref[:, hh, :]
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[:, hh, :].astype(jnp.float32),
                        axis=1, keepdims=True)
        dq = _dq_loop(
            q_ref[:, hh, :], do, lse_ref[hh, :, :], delta,
            lambda j, hh=hh: (k_ref[pl.ds(j * block_k, block_k), hh, :],
                              v_ref[pl.ds(j * block_k, block_k), hh, :]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        dq_ref[:, hh, :] = dq.astype(dq_ref.dtype)


def _dkv_loop(k, v, load_q, *, jk, block_q, block_k, scale, causal,
              seq_q, seq_k, seg_k=None, load_seg_q=None, load_bias=None):
    """Shared dK/dV recurrence. One body for the per-head and
    all-heads-block dKV kernels. load_q(i) -> (q, do, o, lse) blocks.
    Returns (dk, dv), each [block_k, d] f32.
    seg_k/load_seg_q: varlen segment ids; load_bias(i) -> [block_q,
    block_k] additive bias (see _online_softmax)."""
    d = k.shape[-1]
    off = seq_k - seq_q
    segmented = seg_k is not None

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q, do, o, lse = load_q(i)
            delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                            axis=1, keepdims=True)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if load_bias is not None:
                s = s + load_bias(i)
            if masked:
                q_ids = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_ids = jk * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                valid = q_ids < seq_q
                if causal:
                    valid = jnp.logical_and(valid, q_ids + off >= k_ids)
                if segmented:
                    seg_q = load_seg_q(i)  # [block_q, 1]
                    valid = jnp.logical_and(
                        valid, seg_q == seg_k.reshape(1, block_k))
                s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)
            pc = p.astype(do.dtype)
            dv_new = dv + jax.lax.dot_general(
                pc, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta) * scale).astype(q.dtype)
            dk_new = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    num_iters = pl.cdiv(seq_q, block_q)
    carry = (jnp.zeros((block_k, d), jnp.float32),
             jnp.zeros((block_k, d), jnp.float32))
    tail_masked = segmented or seq_q % block_q != 0
    if causal:
        # bottom-right alignment: kv block jk is seen by q rows
        # >= jk*block_k - off. q blocks with min q_id + off >= max k_id
        # are fully unmasked; between the diagonal and there runs masked.
        # (Segmented mode: boundaries cut anywhere, all blocks masked.)
        start_block = jnp.clip((jk * block_k - off) // block_q,
                               0, num_iters)
        first_full = -(-((jk + 1) * block_k - 1 - off) // block_q)  # ceil
        first_full = jnp.clip(first_full, start_block, num_iters)
        carry = jax.lax.fori_loop(start_block, first_full, make_body(True),
                                  carry)
        return jax.lax.fori_loop(first_full, num_iters,
                                 make_body(tail_masked), carry)
    return jax.lax.fori_loop(0, num_iters, make_body(tail_masked), carry)


def _bwd_dkv_kernel_bias(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                         do_ref, dk_ref, dv_ref, *, scale, block_q,
                         causal, seq_q, seq_k):
    # b_ref: [seq_q, block_k] f32 bias column-band for this kv block
    block_k = k_ref.shape[0]
    dk, dv = _dkv_loop(
        k_ref[:], v_ref[:],
        lambda i: (q_ref[pl.ds(i * block_q, block_q), :],
                   do_ref[pl.ds(i * block_q, block_q), :],
                   o_ref[pl.ds(i * block_q, block_q), :],
                   lse_ref[pl.ds(i * block_q, block_q), :]),
        jk=pl.program_id(2), block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k,
        load_bias=lambda i: b_ref[pl.ds(i * block_q, block_q), :]
        .astype(jnp.float32))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dk_ref,
                    dv_ref, *, scale, block_q, causal, seq_q, seq_k, rep):
    """Grid (b, h_kv, kv_blocks). q/do/o refs carry the KV head's GROUP
    of `rep` query heads ([rep, seq_q, d]; lse [rep, seq_q, 1]): dK/dV
    for a KV head sum the contributions of every query head it serves
    (rep == 1 is plain MHA)."""
    block_k = k_ref.shape[0]
    jk = pl.program_id(2)
    k = k_ref[:]
    v = v_ref[:]
    dk_acc = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv_acc = jnp.zeros((block_k, v.shape[-1]), jnp.float32)
    for r in range(rep):
        dk, dv = _dkv_loop(
            k, v,
            lambda i, r=r: (q_ref[r, pl.ds(i * block_q, block_q), :],
                            do_ref[r, pl.ds(i * block_q, block_q), :],
                            o_ref[r, pl.ds(i * block_q, block_q), :],
                            lse_ref[r, pl.ds(i * block_q, block_q), :]),
            jk=jk, block_q=block_q, block_k=block_k,
            scale=scale, causal=causal, seq_q=seq_q, seq_k=seq_k)
        dk_acc = dk_acc + dk
        dv_acc = dv_acc + dv
    dk_ref[:] = dk_acc.astype(dk_ref.dtype)
    dv_ref[:] = dv_acc.astype(dv_ref.dtype)


def _bwd_dkv_kernel_mh(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dk_ref,
                       dv_ref, *, scale, block_q, causal, seq_q, seq_k,
                       n_heads):
    """All-heads-block dK/dV: [B,S,H,D] operands in place. k/v/dk/dv
    refs: [block_k, H, D]; q/do/o: [seq_q, H, D]; lse: [H, seq_q, 1]."""
    block_k = k_ref.shape[0]
    jk = pl.program_id(1)
    for hh in range(n_heads):
        dk, dv = _dkv_loop(
            k_ref[:, hh, :], v_ref[:, hh, :],
            lambda i, hh=hh: (
                q_ref[pl.ds(i * block_q, block_q), hh, :],
                do_ref[pl.ds(i * block_q, block_q), hh, :],
                o_ref[pl.ds(i * block_q, block_q), hh, :],
                lse_ref[hh, pl.ds(i * block_q, block_q), :]),
            jk=jk, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        dk_ref[:, hh, :] = dk.astype(dk_ref.dtype)
        dv_ref[:, hh, :] = dv.astype(dv_ref.dtype)


def _bwd_mh(q, k, v, out, lse, do, causal, block_q, block_k):
    """Backward on [B,S,H,D] with zero layout changes (mh kernels).
    Returns dq/dk/dv in [B,S,H,D]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    dimsem = None
    if not _interpret():
        dimsem = _TPUCompilerParams(
            dimension_semantics=(_PLL, _ARB))
    q_spec = pl.BlockSpec((None, block_q, h, d),
                          lambda bi, i: (bi, i, 0, 0))
    full_q = pl.BlockSpec((None, sq, h, d), lambda bi, i: (bi, 0, 0, 0))
    k_full = pl.BlockSpec((None, sk, h, d), lambda bi, i: (bi, 0, 0, 0))
    lse_spec = pl.BlockSpec((None, h, block_q, 1),
                            lambda bi, i: (bi, 0, i, 0))
    full_lse = pl.BlockSpec((None, h, sq, 1), lambda bi, i: (bi, 0, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_mh, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[q_spec, k_full, k_full, q_spec, lse_spec, q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, sq, h, d), q.dtype),
        interpret=_interpret(),
        compiler_params=dimsem,
    )(q, k, v, out, lse, do)

    kv_spec = pl.BlockSpec((None, block_k, h, d),
                           lambda bi, j: (bi, j, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_mh, scale=scale, block_q=block_q,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h),
        grid=(b, pl.cdiv(sk, block_k)),
        in_specs=[full_q, kv_spec, kv_spec, full_q, full_lse, full_q],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, sk, h, d), k.dtype),
                   jax.ShapeDtypeStruct((b, sk, h, d), v.dtype)],
        interpret=_interpret(),
        compiler_params=dimsem,
    )(q, k, v, out, lse, do)

    return dq, dk, dv


def _bwd_t(qt, kt, vt, ot, lse, dot, causal, block_q, block_k,
           seq_q_real=None, seq_k_real=None):
    """Backward on head-major [B,H,S,D] operands; returns dq/dk/dv in the
    same head-major layout. The custom VJP saves residuals head-major
    (the forward already computed them), so backward only transposes the
    incoming cotangent and the outgoing grads — half the transpose HBM
    traffic of re-deriving all five operands from [B,S,H,D]
    (PERF.md: ~25 ms/step of transposes at the bench shape).
    seq_*_real: logical lengths for padded arrays (see _fwd_t) — kernels
    bound loops/masks on the real lengths, so padded key rows contribute
    nothing and the caller slices padded grad rows off."""
    b, h, sq, d = qt.shape
    h_kv = kt.shape[1]
    assert h % h_kv == 0, (h, h_kv)
    rep = h // h_kv
    sk = kt.shape[2]
    sq_r = seq_q_real or sq
    sk_r = seq_k_real or sk
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)

    q_spec = pl.BlockSpec((None, None, block_q, d),
                          lambda bi, hi, i: (bi, hi, i, 0))
    k_spec_full = pl.BlockSpec((None, None, sk, d),
                               lambda bi, hi, i: (bi, hi // rep, 0, 0))
    lse_spec = pl.BlockSpec((None, None, block_q, 1),
                            lambda bi, hi, i: (bi, hi, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq_r, seq_k=sk_r),
        grid=(b, h, pl.cdiv(sq, block_q)),
        in_specs=[q_spec, k_spec_full, k_spec_full, q_spec, lse_spec, q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, ot, lse, dot)

    # dK/dV: grid over KV heads; each instance reads its whole group of
    # `rep` query heads (block dim1 = rep, block-unit index hi)
    group_q = pl.BlockSpec((None, rep, sq, d),
                           lambda bi, hi, j: (bi, hi, 0, 0))
    group_lse = pl.BlockSpec((None, rep, sq, 1),
                             lambda bi, hi, j: (bi, hi, 0, 0))
    kv_spec = pl.BlockSpec((None, None, block_k, d),
                           lambda bi, hi, j: (bi, hi, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          causal=causal, seq_q=sq_r, seq_k=sk_r, rep=rep),
        grid=(b, h_kv, pl.cdiv(sk, block_k)),
        in_specs=[group_q, kv_spec, kv_spec, group_q, group_lse, group_q],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h_kv, sk, d), kt.dtype),
                   jax.ShapeDtypeStruct((b, h_kv, sk, d), vt.dtype)],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, ot, lse, dot)

    return dq, dk, dv


def _bwd(q, k, v, out, lse, do, causal, block_q, block_k):
    dq, dk, dv = _bwd_t(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), jnp.swapaxes(out, 1, 2),
                        lse, jnp.swapaxes(do, 1, 2), causal,
                        block_q, block_k)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


# =========================== public entry ===========================

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, causal, block_q, block_k, seq_q_real=None,
                seq_k_real=None):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, _ = _fwd_t(qt, kt, vt, causal, block_q, block_k,
                    seq_q_real, seq_k_real)
    return jnp.swapaxes(out, 1, 2)


def _flash_core_fwd(q, k, v, causal, block_q, block_k, seq_q_real=None,
                    seq_k_real=None):
    # residuals saved HEAD-MAJOR: forward already computed the [B,H,S,D]
    # transposes, so backward reuses them instead of re-transposing all
    # five operands from [B,S,H,D] — only the cotangent (in) and the three
    # grads (out) cross layouts in the backward pass
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _fwd_t(qt, kt, vt, causal, block_q, block_k,
                        seq_q_real, seq_k_real)
    return jnp.swapaxes(out_t, 1, 2), (qt, kt, vt, out_t, lse)


def _flash_core_bwd(causal, block_q, block_k, seq_q_real, seq_k_real,
                    res, g):
    qt, kt, vt, ot, lse = res
    dq, dk, dv = _bwd_t(qt, kt, vt, ot, lse, jnp.swapaxes(g, 1, 2),
                        causal, block_q, block_k, seq_q_real, seq_k_real)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core_mh(q, k, v, causal, block_q, block_k):
    """Transpose-free core: all-heads-block kernels end to end. Same
    numerics as _flash_core (shared loop bodies); no [B,H,S,D] arrays
    ever materialize. Selected by FLAGS_flash_layout=mh once the on-chip
    A/B (tools/chip_session.py layout_ab) proves it faster."""
    out, _ = _fwd_mh(q, k, v, causal, block_q, block_k)
    return out


def _flash_core_mh_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _fwd_mh(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_core_mh_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _bwd_mh(q, k, v, out, lse, g, causal, block_q, block_k)


_flash_core_mh.defvjp(_flash_core_mh_fwd, _flash_core_mh_bwd)


# ================= mixed-layout (kv-native) kernels =================
#
# Round-5 on-chip bisect (tools/chip_session.py phase_mh_bisect plus a
# follow-up compile ladder on the real toolchain): the deployed Mosaic
# rejects a middle-dim-squeezed load as a dot LHS ("infer-vector-layout:
# unsupported shape cast") and any DYNAMIC index into a middle dim
# ("cannot statically prove that index ... is a multiple of 4"), but it
# accepts
#   (a) STATIC middle-dim squeezes as dot RHS operands,
#   (b) static middle-dim-squeezed stores, and
#   (c) leading-dim indexing of head-major blocks (free: offset only).
# Every dot in the shared flash loops uses K/V strictly as the RHS
# (_online_softmax, _dq_loop, _dkv_loop), so K/V/dK/dV can stay in the
# model's NATIVE [B,S,H,D] layout end to end while Q/O/dO/dQ travel
# head-major: the K/V transposes in forward and the dK/dV transposes in
# backward never exist. The round-5 xprof trace put the flash layout
# transposes at ~66 ms/step (20%) of the GPT-125M bench step; this tier
# removes half of them (the full-mh core that would remove the rest is
# what the toolchain rejects, see docs/ATTENTION.md "layout A/B").


def _fwd_kernel_kv(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                   block_k, causal, seq_q, seq_k, n_heads, rep):
    """q_ref/o_ref: [H, block_q, D] head-major; k_ref/v_ref:
    [seq_k, Hkv, D] native; lse_ref: [H, block_q, 1]. Heads walk a
    static Python loop (dynamic head indices do not lower, see above);
    per-head K/V loads are static middle-dim squeezes used only as dot
    RHS."""
    block_q = q_ref.shape[1]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        hkv = hh // rep
        out, lse = _online_softmax(
            q_ref[hh],
            lambda j, hkv=hkv: (
                k_ref[pl.ds(j * block_k, block_k), hkv, :],
                v_ref[pl.ds(j * block_k, block_k), hkv, :]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        o_ref[hh] = out.astype(o_ref.dtype)
        lse_ref[hh] = lse.astype(jnp.float32)


def _kv_dimsem():
    # vmem_limit_bytes: the kv kernels keep all heads' loop intermediates
    # on the Mosaic stack (statically unrolled head walk) and need
    # ~20-35 MiB at training block sizes — above the 16 MiB default but
    # real headroom on v5e's 128 MiB VMEM. Raising the limit PER KERNEL
    # (instead of the program-wide xla_tpu_scoped_vmem_limit_kib flag)
    # leaves XLA's own ops on the default budget — a program-wide raise
    # makes large fusion/transpose ops pick >40 MiB scoped strategies
    # that then fail allocation (observed on-chip this round).
    if _interpret():
        return None
    return _TPUCompilerParams(
        dimension_semantics=(_PLL, _ARB),
        vmem_limit_bytes=34 * 1024 * 1024)


def _fwd_kv(qt, k, v, causal, block_q, block_k):
    """Forward with head-major Q/O ([B,H,Sq,D]) and native-layout K/V
    ([B,Sk,Hkv,D]); GQA reads the shrunken KV directly (hh // rep).
    Returns (out_t [B,H,Sq,D], lse [B,H,Sq,1])."""
    b, h, sq, d = qt.shape
    sk, h_kv = k.shape[1], k.shape[2]
    assert h % h_kv == 0, (h, h_kv)
    rep = h // h_kv
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_kv, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h,
                          rep=rep),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((None, h, block_q, d),
                         lambda bi, qi: (bi, 0, qi, 0)),
            pl.BlockSpec((None, sk, h_kv, d),
                         lambda bi, qi: (bi, 0, 0, 0)),
            pl.BlockSpec((None, sk, h_kv, d),
                         lambda bi, qi: (bi, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, h, block_q, d),
                         lambda bi, qi: (bi, 0, qi, 0)),
            pl.BlockSpec((None, h, block_q, 1),
                         lambda bi, qi: (bi, 0, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(qt, k, v)
    return out, lse


def _bwd_dq_kernel_kv(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                      dq_ref, *, scale, block_k, causal, seq_q, seq_k,
                      n_heads, rep):
    """q/o/do/dq refs: [H, block_q, D] head-major; k/v: [seq_k, Hkv, D]
    native; lse: [H, block_q, 1]."""
    block_q = q_ref.shape[1]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        hkv = hh // rep
        do = do_ref[hh]
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[hh].astype(jnp.float32),
                        axis=1, keepdims=True)
        dq = _dq_loop(
            q_ref[hh], do, lse_ref[hh], delta,
            lambda j, hkv=hkv: (
                k_ref[pl.ds(j * block_k, block_k), hkv, :],
                v_ref[pl.ds(j * block_k, block_k), hkv, :]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        dq_ref[hh] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel_kv(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                       dk_ref, dv_ref, *, scale, block_q, causal, seq_q,
                       seq_k, rep):
    """k/v/dk/dv refs: [block_k, Hkv, D] native (squeezed static stores);
    q/o/do: [H, seq_q, D] head-major; lse: [H, seq_q, 1]. dK/dV for a KV
    head sum the contributions of its whole query group (rep == 1 is
    plain MHA)."""
    block_k = k_ref.shape[0]
    jk = pl.program_id(1)
    for hkv in range(k_ref.shape[1]):
        k = k_ref[:, hkv, :]
        v = v_ref[:, hkv, :]
        dk_acc = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
        dv_acc = jnp.zeros((block_k, v.shape[-1]), jnp.float32)
        for r in range(rep):
            hh = hkv * rep + r
            dk, dv = _dkv_loop(
                k, v,
                lambda i, hh=hh: (
                    q_ref[hh, pl.ds(i * block_q, block_q), :],
                    do_ref[hh, pl.ds(i * block_q, block_q), :],
                    o_ref[hh, pl.ds(i * block_q, block_q), :],
                    lse_ref[hh, pl.ds(i * block_q, block_q), :]),
                jk=jk, block_q=block_q, block_k=block_k, scale=scale,
                causal=causal, seq_q=seq_q, seq_k=seq_k)
            dk_acc = dk_acc + dk
            dv_acc = dv_acc + dv
        # The deployed Mosaic cannot shape-cast a dot-accumulator value
        # into a middle-dim-squeezed STORE directly ("infer-vector-layout:
        # unsupported shape cast"); storing a splat zero first (constants
        # are layout-flexible) and re-loading gives the accumulator a
        # store-compatible layout via a supported relayout. The extra
        # VMEM round-trip is noise next to the dK/dV HBM transposes this
        # kernel eliminates.
        dk_ref[:, hkv, :] = jnp.zeros((block_k, k.shape[-1]),
                                      dk_ref.dtype)
        dv_ref[:, hkv, :] = jnp.zeros((block_k, v.shape[-1]),
                                      dv_ref.dtype)
        dk_ref[:, hkv, :] = (dk_ref[:, hkv, :].astype(jnp.float32) +
                             dk_acc).astype(dk_ref.dtype)
        dv_ref[:, hkv, :] = (dv_ref[:, hkv, :].astype(jnp.float32) +
                             dv_acc).astype(dv_ref.dtype)


def _bwd_kv(qt, k, v, ot, lse, dot, causal, block_q, block_k):
    """Backward companion of _fwd_kv: head-major q/o/do in, head-major dq
    + NATIVE-layout dk/dv out (no transposes behind dK/dV)."""
    b, h, sq, d = qt.shape
    sk, h_kv = k.shape[1], k.shape[2]
    rep = h // h_kv
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)

    hm_spec = pl.BlockSpec((None, h, block_q, d),
                           lambda bi, qi: (bi, 0, qi, 0))
    hm_lse = pl.BlockSpec((None, h, block_q, 1),
                          lambda bi, qi: (bi, 0, qi, 0))
    kv_full = pl.BlockSpec((None, sk, h_kv, d),
                           lambda bi, qi: (bi, 0, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_kv, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h,
                          rep=rep),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[hm_spec, kv_full, kv_full, hm_spec, hm_lse, hm_spec],
        out_specs=hm_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(qt, k, v, ot, lse, dot)

    hm_full = pl.BlockSpec((None, h, sq, d), lambda bi, kj: (bi, 0, 0, 0))
    hm_full_lse = pl.BlockSpec((None, h, sq, 1),
                               lambda bi, kj: (bi, 0, 0, 0))
    kv_spec = pl.BlockSpec((None, block_k, h_kv, d),
                           lambda bi, kj: (bi, kj, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_kv, scale=scale,
                          block_q=block_q, causal=causal, seq_q=sq,
                          seq_k=sk, rep=rep),
        grid=(b, pl.cdiv(sk, block_k)),
        in_specs=[hm_full, kv_spec, kv_spec, hm_full, hm_full_lse,
                  hm_full],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, sk, h_kv, d), k.dtype),
                   jax.ShapeDtypeStruct((b, sk, h_kv, d), v.dtype)],
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(qt, k, v, ot, lse, dot)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core_kv(q, k, v, causal, block_q, block_k):
    """Mixed-layout core: only Q and O (and in backward dO/dQ) cross the
    [B,S,H,D]<->[B,H,S,D] boundary; K/V/dK/dV stay native. Numerics are
    the shared flash loops — bit-identical to _flash_core."""
    out_t, _ = _fwd_kv(_to_hm(q), k, v, causal, block_q, block_k)
    return _from_hm(out_t)


def _flash_core_kv_fwd(q, k, v, causal, block_q, block_k):
    qt = _to_hm(q)
    out_t, lse = _fwd_kv(qt, k, v, causal, block_q, block_k)
    return _from_hm(out_t), (qt, k, v, out_t, lse)


def _flash_core_kv_bwd(causal, block_q, block_k, res, g):
    qt, k, v, ot, lse = res
    dq_t, dk, dv = _bwd_kv(qt, k, v, ot, lse, _to_hm(g),
                           causal, block_q, block_k)
    return _from_hm(dq_t), dk, dv


_flash_core_kv.defvjp(_flash_core_kv_fwd, _flash_core_kv_bwd)

# ----- Pallas layout relayout ([B,S,H,D] <-> [B,H,S,D]) -----
#
# Two reasons these are Pallas kernels instead of jnp.swapaxes:
# 1. Speed: the round-5 xprof trace measured XLA's flash layout
#    transposes at ~209 GB/s apparent bandwidth (~25% of v5e roofline)
#    — ~66 ms/step at the GPT-125M bench shape.
# 2. The kv-native kernels need a raised per-kernel VMEM limit, and the
#    deployed toolchain applies the largest per-kernel limit to the
#    WHOLE program's scoped-vmem check, under which XLA's own big
#    transpose fusions pick >40 MiB stack strategies and fail to
#    compile. Pallas relayouts keep every layout move inside kernels
#    that carry their own budgets.
# Only the VPU touches data here (squeezed loads/stores are the
# bisect-proven headwalk pattern), so lowering is compile-safe on the
# deployed Mosaic.


def _relayout_kernel_to_hm(x_ref, o_ref, *, n_heads):
    # x_ref: [block_s, H, D] native; o_ref: [H, block_s, D] head-major.
    # A middle-squeezed LOAD and a leading-index STORE carry different
    # Mosaic layout flavors; a bare store needs an unsupported shape
    # cast. Storing a splat zero first (constants are layout-flexible)
    # and accumulating routes the conversion through a supported
    # relayout instead (same trick as the dKV store).
    block_s, _, d = x_ref.shape
    for hh in range(n_heads):
        o_ref[hh] = jnp.zeros((block_s, d), o_ref.dtype)
        o_ref[hh] = o_ref[hh] + x_ref[:, hh, :]


def _relayout_kernel_from_hm(x_ref, o_ref, *, n_heads):
    # x_ref: [H, block_s, D] head-major; o_ref: [block_s, H, D] native
    _, block_s, d = x_ref.shape
    for hh in range(n_heads):
        o_ref[:, hh, :] = jnp.zeros((block_s, d), o_ref.dtype)
        o_ref[:, hh, :] = o_ref[:, hh, :] + x_ref[hh]


def _relayout_block(s):
    # biggest multiple of 8 dividing s, capped at 512 rows per block
    b = min(512, s)
    b -= b % 8
    while b > 8 and s % b:
        b -= 8
    return max(b, 8)


@jax.custom_vjp
def _to_hm(x):
    """[B,S,H,D] -> [B,H,S,D] as a Pallas copy on TPU (jnp.swapaxes on
    the interpreter). Adjoint is _from_hm."""
    b, s, h, d = x.shape
    if _interpret():
        return jnp.swapaxes(x, 1, 2)
    bs = _relayout_block(s)
    return pl.pallas_call(
        functools.partial(_relayout_kernel_to_hm, n_heads=h),
        grid=(b, pl.cdiv(s, bs)),
        in_specs=[pl.BlockSpec((None, bs, h, d),
                               lambda bi, si: (bi, si, 0, 0))],
        out_specs=pl.BlockSpec((None, h, bs, d),
                               lambda bi, si: (bi, 0, si, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), x.dtype),
        compiler_params=_kv_dimsem(),
    )(x)


@jax.custom_vjp
def _from_hm(xt):
    """[B,H,S,D] -> [B,S,H,D]; adjoint is _to_hm."""
    b, h, s, d = xt.shape
    if _interpret():
        return jnp.swapaxes(xt, 1, 2)
    bs = _relayout_block(s)
    return pl.pallas_call(
        functools.partial(_relayout_kernel_from_hm, n_heads=h),
        grid=(b, pl.cdiv(s, bs)),
        in_specs=[pl.BlockSpec((None, h, bs, d),
                               lambda bi, si: (bi, 0, si, 0))],
        out_specs=pl.BlockSpec((None, bs, h, d),
                               lambda bi, si: (bi, si, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, s, h, d), xt.dtype),
        compiler_params=_kv_dimsem(),
    )(xt)


def _to_hm_fwd(x):
    return _to_hm(x), None


def _to_hm_bwd(_, g):
    return (_from_hm(g),)


def _from_hm_fwd(xt):
    return _from_hm(xt), None


def _from_hm_bwd(_, g):
    return (_to_hm(g),)


_to_hm.defvjp(_to_hm_fwd, _to_hm_bwd)
_from_hm.defvjp(_from_hm_fwd, _from_hm_bwd)

# ================= flat-native kernels ([B, S, H*D] views) =================
#
# The end state of the round-5 layout work. The deployed Mosaic accepts
# STATIC 64-lane slices of a flat [*, H*D] block as MXU dot operands and
# as stores (compile-proven on-chip), which makes head-major arrays
# unnecessary ALTOGETHER:
#   - q/k/v/o and all gradients stay [B, S, H*D] — the trailing dims
#     (S, 768) are tile-aligned, so none of the 2-2.7x T(8,128) padding
#     that [B,H,S,D]/[B,S,H,D] 4-D arrays with D=64 pay in HBM;
#   - zero transposes and zero relayout copies: XLA sees the same flat
#     layout the surrounding GEMMs use (the [B,S,3,H,D] reshape/unbind
#     around the qkv projection is a free bitcast);
#   - no layout-pinned custom-call boundary for XLA to insert scoped-
#     stack transpose copies around (the failure mode that killed the
#     4-D kv-native tier at raised VMEM limits: those copies size
#     themselves just over whatever per-kernel limit leaks into the
#     program-wide scoped check).
# Heads walk a static Python loop; per-head operands are lane slices
# hh*D:(hh+1)*D. The shared recurrences (_online_softmax, _dq_loop,
# _dkv_loop) are reused as-is — numerics identical to every other core.


def _fwd_kernel_flat(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                     block_k, causal, seq_q, seq_k, n_heads, rep, d):
    # q_ref/o_ref: [block_q, H*D]; k_ref/v_ref: [seq_k, Hkv*D];
    # lse_ref: [H, block_q, 1]
    block_q = q_ref.shape[0]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        lo = (hh // rep) * d
        out, lse = _online_softmax(
            q_ref[:, hh * d:(hh + 1) * d],
            lambda j, lo=lo: (
                k_ref[pl.ds(j * block_k, block_k), lo:lo + d],
                v_ref[pl.ds(j * block_k, block_k), lo:lo + d]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        o_ref[:, hh * d:(hh + 1) * d] = out.astype(o_ref.dtype)
        lse_ref[hh] = lse.astype(jnp.float32)


def _fwd_flat(q, k, v, h, causal, block_q, block_k):
    """Forward on flat [B,Sq,H*D] q and [B,Sk,Hkv*D] k/v.
    Returns (out [B,Sq,H*D], lse [B,H,Sq,1])."""
    b, sq, hd = q.shape
    d = hd // h
    sk, hkvd = k.shape[1], k.shape[2]
    h_kv = hkvd // d
    rep = h // h_kv
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_flat, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk, n_heads=h,
                          rep=rep, d=d),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, hd),
                         lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((None, sk, hkvd), lambda bi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, sk, hkvd), lambda bi, qi: (bi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, hd),
                         lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((None, h, block_q, 1),
                         lambda bi, qi: (bi, 0, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(q, k, v)
    return out, lse


def _bwd_dq_kernel_flat(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                        dq_ref, *, scale, block_k, causal, seq_q, seq_k,
                        n_heads, rep, d):
    block_q = q_ref.shape[0]
    iq = pl.program_id(1)
    for hh in range(n_heads):
        lo = (hh // rep) * d
        sl = slice(hh * d, (hh + 1) * d)
        do = do_ref[:, sl]
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[:, sl].astype(jnp.float32),
                        axis=1, keepdims=True)
        dq = _dq_loop(
            q_ref[:, sl], do, lse_ref[hh], delta,
            lambda j, lo=lo: (
                k_ref[pl.ds(j * block_k, block_k), lo:lo + d],
                v_ref[pl.ds(j * block_k, block_k), lo:lo + d]),
            iq=iq, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        dq_ref[:, sl] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel_flat(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                         dk_ref, dv_ref, *, scale, block_q, causal,
                         seq_q, seq_k, n_heads, rep, d):
    block_k = k_ref.shape[0]
    jk = pl.program_id(1)
    h_kv = n_heads // rep
    for hkv in range(h_kv):
        ksl = slice(hkv * d, (hkv + 1) * d)
        k = k_ref[:, ksl]
        v = v_ref[:, ksl]
        dk_acc = jnp.zeros((block_k, d), jnp.float32)
        dv_acc = jnp.zeros((block_k, d), jnp.float32)
        for r in range(rep):
            hh = hkv * rep + r
            qsl = slice(hh * d, (hh + 1) * d)
            dk, dv = _dkv_loop(
                k, v,
                lambda i, qsl=qsl, hh=hh: (
                    q_ref[pl.ds(i * block_q, block_q), qsl],
                    do_ref[pl.ds(i * block_q, block_q), qsl],
                    o_ref[pl.ds(i * block_q, block_q), qsl],
                    lse_ref[hh, pl.ds(i * block_q, block_q), :]),
                jk=jk, block_q=block_q, block_k=block_k, scale=scale,
                causal=causal, seq_q=seq_q, seq_k=seq_k)
            dk_acc = dk_acc + dk
            dv_acc = dv_acc + dv
        dk_ref[:, ksl] = dk_acc.astype(dk_ref.dtype)
        dv_ref[:, ksl] = dv_acc.astype(dv_ref.dtype)


def _bwd_flat(q, k, v, out, lse, do, h, causal, block_q, block_k):
    """Backward companion of _fwd_flat: everything stays [B,S,H*D]."""
    b, sq, hd = q.shape
    d = hd // h
    sk, hkvd = k.shape[1], k.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    rep = hd // hkvd

    q_spec = pl.BlockSpec((None, block_q, hd), lambda bi, qi: (bi, qi, 0))
    lse_spec = pl.BlockSpec((None, h, block_q, 1),
                            lambda bi, qi: (bi, 0, qi, 0))
    kv_full = pl.BlockSpec((None, sk, hkvd), lambda bi, qi: (bi, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_flat, scale=scale,
                          block_k=block_k, causal=causal, seq_q=sq,
                          seq_k=sk, n_heads=h, rep=rep, d=d),
        grid=(b, pl.cdiv(sq, block_q)),
        in_specs=[q_spec, kv_full, kv_full, q_spec, lse_spec, q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(q, k, v, out, lse, do)

    q_full = pl.BlockSpec((None, sq, hd), lambda bi, kj: (bi, 0, 0))
    lse_full = pl.BlockSpec((None, h, sq, 1), lambda bi, kj: (bi, 0, 0, 0))
    kv_spec = pl.BlockSpec((None, block_k, hkvd),
                           lambda bi, kj: (bi, kj, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_flat, scale=scale,
                          block_q=block_q, causal=causal, seq_q=sq,
                          seq_k=sk, n_heads=h, rep=rep, d=d),
        grid=(b, pl.cdiv(sk, block_k)),
        in_specs=[q_full, kv_spec, kv_spec, q_full, lse_full, q_full],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, sk, hkvd), k.dtype),
                   jax.ShapeDtypeStruct((b, sk, hkvd), v.dtype)],
        interpret=_interpret(),
        compiler_params=_kv_dimsem(),
    )(q, k, v, out, lse, do)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core_flat(q, k, v, causal, block_q, block_k):
    """Flat-native core: public [B,S,H,D] in/out, but every kernel
    operand rides an unpadded [B,S,H*D] view (free reshape). Zero
    transposes, zero relayouts, zero padded arrays. Numerics are the
    shared flash loops — identical to _flash_core."""
    b, sq, h, d = q.shape
    out, _ = _fwd_flat(q.reshape(b, sq, h * d),
                       k.reshape(b, k.shape[1], -1),
                       v.reshape(b, v.shape[1], -1),
                       h, causal, block_q, block_k)
    return out.reshape(b, sq, h, d)


def _flash_core_flat_fwd(q, k, v, causal, block_q, block_k):
    b, sq, h, d = q.shape
    qf = q.reshape(b, sq, h * d)
    kf = k.reshape(b, k.shape[1], -1)
    vf = v.reshape(b, v.shape[1], -1)
    out, lse = _fwd_flat(qf, kf, vf, h, causal, block_q, block_k)
    return out.reshape(b, sq, h, d), (qf, kf, vf, out, lse, h, d)


def _flash_core_flat_bwd(causal, block_q, block_k, res, g):
    qf, kf, vf, out, lse, h, d = res
    b, sq, hd = qf.shape
    dq, dk, dv = _bwd_flat(qf, kf, vf, out, lse,
                           g.reshape(b, sq, hd), h, causal,
                           block_q, block_k)
    return (dq.reshape(b, sq, h, d),
            dk.reshape(b, kf.shape[1], -1, d),
            dv.reshape(b, vf.shape[1], -1, d))


_flash_core_flat.defvjp(_flash_core_flat_fwd, _flash_core_flat_bwd)

_KV_VMEM_BOUND = 8 * 1024 * 1024


def _gate_reject(gate: str, reason: str, q, k, blocks) -> None:
    """Counter + flight-recorder evidence for a kernel-tier gate reject:
    the silent-fallback class of failure (ADVICE r5) becomes a metric
    (`flash.gate_reject{gate,reason}`) and a ring event carrying the
    shapes and the blocks the gate actually estimated."""
    _metrics.inc("flash.gate_reject", gate=gate, reason=reason)
    _flight.record("flash.gate_reject", gate=gate, reason=reason,
                   q_shape=list(q.shape), kv_shape=list(k.shape),
                   blocks=list(blocks))


def _kv_native_ok(q, k, block_q=512, block_k=512, _gate="kv") -> bool:
    """VMEM feasibility of the kv-native AND flat kernels (same block
    geometry): the forward holds full K+V per batch row; the dKV kernel
    holds full-sequence q/o/do per head walk. Past the bound, the
    transpose core (block-sliced K/V) is the safe path.

    block_q/block_k are the blocks that will REALLY run (the dispatch
    site passes the tuned values; advisor-medium r5: the old gate
    hardcoded a 512 estimate, so 1024-tuned blocks sailed through and
    died at Mosaic compile time).  They are resolved through _pick_block
    exactly as the kernels will resolve them."""
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    if sq % 8 != 0 or sk % 8 != 0:
        # off-8 lengths run padded through the transpose core (the
        # dispatch pads before gating); a direct probe gets False, not
        # the _pick_block ValueError
        return False
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    esz = q.dtype.itemsize
    fwd_bytes = 2 * sk * h_kv * d * esz + 2 * h * bq * d * esz
    dkv_bytes = (3 * h * sq * d * esz + 4 * h * sq +
                 4 * bk * h_kv * d * esz)
    if max(fwd_bytes, dkv_bytes) > _KV_VMEM_BOUND:
        _gate_reject(_gate, "vmem", q, k, (bq, bk))
        return False
    return True


def _flat_static_ok(q, k) -> bool:
    """Block-INDEPENDENT flat eligibility: lane alignment — the flat
    kernels slice per-head lane windows out of an [*, H*D] block and
    were real-compile-proven only with the flat width a multiple of the
    128-lane tile — AND per-head slice width ``d % 64 == 0`` (the only
    compile-proven head width; off-64 widths shape-cast inside the lane
    slice and the deployed Mosaic rejects them).  The dispatch site
    checks this BEFORE layout-tagged block tuning, so an ineligible
    shape never launches an autotune search timing the flat core it can
    never run.  Rejects surface through the flight recorder."""
    h, d = q.shape[2], q.shape[3]
    h_kv = k.shape[2]
    if (h * d) % 128 != 0 or (h_kv * d) % 128 != 0:
        _gate_reject("flat", "lane_align", q, k, ())
        return False
    if d % 64 != 0:
        _gate_reject("flat", "head_width", q, k, ())
        return False
    return True


def _flat_native_ok(q, k, block_q=512, block_k=512) -> bool:
    """Full flat-kernel eligibility: the block-independent gates of
    _flat_static_ok plus the VMEM bound of _kv_native_ok at the blocks
    that will really run.  (The kv-native kernels index 4-D [S,Hkv,D]
    blocks and need neither flat-specific gate.)"""
    if not _flat_static_ok(q, k):
        return False
    return _kv_native_ok(q, k, block_q, block_k, _gate="flat")


def _layout_flag() -> str:
    import os

    return os.environ.get("FLAGS_flash_layout", _DEFAULT_LAYOUT)


# ===================== biased (additive-mask) core =====================

def _bias_idx(bias_shape, b_dims):
    """Index map for a broadcastable [Bb, Hb, ., .] bias: size-1 batch /
    head dims pin to block 0."""
    has_b = 1 if bias_shape[0] != 1 else 0
    has_h = 1 if bias_shape[1] != 1 else 0
    if b_dims == "q":  # fwd/dq: [block_q, sk] row band, idx by q block
        return lambda bi, hi, i: (bi * has_b, hi * has_h, i, 0)
    return lambda bi, hi, j: (bi * has_b, hi * has_h, 0, j)  # dkv band


def _fwd_tb(qt, kt, vt, bias, causal, block_q, block_k):
    """Biased forward, head-major operands; bias [Bb, Hb, Sq, Sk] f32
    (Bb/Hb broadcastable). Returns (out_t, lse)."""
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_bias, scale=scale, block_k=block_k,
                          causal=causal, seq_q=sq, seq_k=sk),
        grid=(b, h, pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, sk, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, sk, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, sk),
                         _bias_idx(bias.shape, "q")),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, bias)
    return out, lse


def _bwd_tb(qt, kt, vt, bias, ot, lse, dot, causal, block_q, block_k):
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)

    q_spec = pl.BlockSpec((None, None, block_q, d),
                          lambda bi, hi, i: (bi, hi, i, 0))
    full_q = pl.BlockSpec((None, None, sq, d),
                          lambda bi, hi, i: (bi, hi, 0, 0))
    full_lse = pl.BlockSpec((None, None, sq, 1),
                            lambda bi, hi, i: (bi, hi, 0, 0))
    k_full = pl.BlockSpec((None, None, sk, d),
                          lambda bi, hi, i: (bi, hi, 0, 0))
    lse_spec = pl.BlockSpec((None, None, block_q, 1),
                            lambda bi, hi, i: (bi, hi, i, 0))
    bias_q = pl.BlockSpec((None, None, block_q, sk),
                          _bias_idx(bias.shape, "q"))
    bias_k = pl.BlockSpec((None, None, sq, block_k),
                          _bias_idx(bias.shape, "k"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_bias, scale=scale,
                          block_k=block_k, causal=causal, seq_q=sq,
                          seq_k=sk),
        grid=(b, h, pl.cdiv(sq, block_q)),
        in_specs=[q_spec, k_full, k_full, bias_q, q_spec, lse_spec,
                  q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, bias, ot, lse, dot)

    kv_spec = pl.BlockSpec((None, None, block_k, d),
                           lambda bi, hi, j: (bi, hi, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_bias, scale=scale,
                          block_q=block_q, causal=causal, seq_q=sq,
                          seq_k=sk),
        grid=(b, h, pl.cdiv(sk, block_k)),
        in_specs=[full_q, kv_spec, kv_spec, bias_k, full_q, full_lse,
                  full_q],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), kt.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), vt.dtype)],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, bias, ot, lse, dot)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core_b(q, k, v, bias, causal, block_q, block_k):
    """Additive-bias core (rel-pos bias, ALiBi, additive/boolean masks on
    the fused tier): bias streams blockwise into the logits — the
    [Sq, Sk] score matrix never materializes. The bias itself receives NO
    gradient (zero cotangent): the entry only routes stop-gradient masks
    here; trainable biases take the reference path."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, _ = _fwd_tb(qt, kt, vt, bias, causal, block_q, block_k)
    return jnp.swapaxes(out, 1, 2)


def _flash_core_b_fwd(q, k, v, bias, causal, block_q, block_k):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _fwd_tb(qt, kt, vt, bias, causal, block_q, block_k)
    return jnp.swapaxes(out_t, 1, 2), (qt, kt, vt, bias, out_t, lse)


def _flash_core_b_bwd(causal, block_q, block_k, res, g):
    qt, kt, vt, bias, ot, lse = res
    dq, dk, dv = _bwd_tb(qt, kt, vt, bias, ot, lse,
                         jnp.swapaxes(g, 1, 2), causal, block_q, block_k)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2), jnp.zeros_like(bias))


_flash_core_b.defvjp(_flash_core_b_fwd, _flash_core_b_bwd)


def _biased_flash_ok(q, k, mask) -> bool:
    """Gate for the biased kernel path: MHA only (the grouped dKV kernel
    has no bias plumbing), block-friendly lengths (the dKV bias band's
    trailing block dim must tile to 128), rank-4 broadcastable mask."""
    if k.shape[2] != q.shape[2]:
        return False
    sq, sk = q.shape[1], k.shape[1]
    if sq % 8 != 0 or sk % 128 != 0:
        return False
    if getattr(mask, "ndim", 0) != 4:
        return False
    mb, mh, msq, msk = mask.shape
    return (mb in (1, q.shape[0]) and mh in (1, q.shape[2])
            and msq == sq and msk == sk)


def _expand_gqa_kv(q, k, v):
    """Expand GQA KV heads to the query head count (consecutive-group
    semantics, matching the kernels' `hi // rep` maps). The ONE shared
    expansion used by every non-grouped path."""
    if k.shape[2] != q.shape[2]:
        assert q.shape[2] % k.shape[2] == 0, (q.shape, k.shape)
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return q, k, v


def _ref_attention(q, k, v, mask, is_causal):
    # flat-layout reference: the einsums contract directly on the native
    # [B,S,H,D] operands (dot_general batches over non-leading (b, h) —
    # no operand relayout), so the only explicit transpose left is the
    # [B,H,Sq,D] -> [B,Sq,H,D] output reorder. Same contraction order as
    # the old swapaxes spelling — bit-identical values, 4x fewer
    # stablehlo.transpose ops (PT401; measured on the audit proxy).
    d = q.shape[-1]
    q, k, v = _expand_gqa_kv(q, k, v)
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _tuned_blocks(b, sq, sk, h, d, dtype, causal, h_kv=None,
                  biased=False, layout=None):
    """Autotuned (block_q, block_k) for this attention signature
    (paddle/phi/kernels/autotune role; cached per signature on disk).

    Tuned on a fwd+bwd run — training is the dominant workload and the
    same (block_q, block_k) pair parameterizes both directions through
    the custom VJP. Measured at B32 H12 S1024 D64 bf16: tuned (1024,1024)
    fwd ≈ 1.3 ms vs 128x128 ≈ 6.0 ms (PERF.md).

    layout: the kernel tier that will consume the blocks.  kv/flat/mh
    layouts tune under their OWN cache signature (``|Lkv`` etc.) —
    advisor-low r5: the kv/flat cores have different VMEM geometry than
    the transpose core, so silently reusing transpose-tuned blocks is
    wrong.  A transpose-tuned entry existing while the layout entry is
    cold is counted as `autotune.cross_layout_reject` (the refusal is
    deliberate and now visible).  `layout=None`/"transpose" keeps the
    original signature, so existing on-disk caches stay valid."""
    from . import autotune

    # curated candidate pairs, preference-ordered by the round-5 hardware
    # sweep (PERF.md: (512, 1024) wins fwd+bwd at BOTH the GPT-125M bench
    # shape, 3.18 ms vs 4.23 for the old (256, 512) default, and the
    # LLaMA-class B8 H16 S2048 D128 shape). The full {128..1024}^2 grid
    # costs ~16 TPU compiles of fwd+bwd per new signature (~10 min
    # through a tunnel); these six cover the measured-good region
    pairs = ((512, 1024), (1024, 1024), (512, 512), (256, 512),
             (256, 256), (128, 128))

    def vmem_est(bq, bk):
        # f32 logits block (s and p live together) + full K/V + q/o/acc;
        # must leave headroom in the ~16 MB/core VMEM budget. GQA: the
        # grouped dK/dV kernel additionally keeps rep x seq_q x d of
        # q/o/do resident (block-size independent, but it eats the same
        # budget the logits compete for).
        itemsize = jnp.dtype(dtype).itemsize
        group = (3 * (h // h_kv) * sq * d * itemsize
                 if h_kv and h_kv != h else 0)
        # biased kernels hold an f32 bias band: [bq, sk] (fwd/dQ) or
        # [sq, bk] (dKV) — budget the larger
        bias_band = max(bq * sk, sq * bk) * 4 if biased else 0
        return (2 * bq * bk * 4 + 2 * sk * d * itemsize
                + 2 * bq * d * itemsize + bq * d * 4 + group
                + bias_band)

    cands = [(bq, bk)
             for bq, bk in pairs
             if sq % bq == 0 and sk % bk == 0 and bq <= sq and bk <= sk
             and vmem_est(bq, bk) <= 12 * 1024 * 1024]
    # static default = best measured pair that FITS this shape (pairs are
    # preference-ordered and vmem-filtered above), so an autotune-cold run
    # (fresh checkout, FLAGS_use_autotune off, 3-minute tunnel window)
    # still gets the hardware winner instead of a conservative constant.
    # The default is also what a failed tuning run falls back to, and it
    # runs UNVALIDATED — so it gets a tighter 8 MB bound (vmem_est omits
    # backward-only accumulators), falling back to the smallest fitting
    # pair rather than the most aggressive one
    default = next(
        (c for c in cands if vmem_est(*c) <= 8 * 1024 * 1024),
        cands[-1] if cands else (_pick_block(sq, DEFAULT_BLOCK_Q),
                                 _pick_block(sk, DEFAULT_BLOCK_K)))
    if len(cands) <= 1:
        return default

    lt = layout if layout in ("kv", "flat", "mh") else None

    def run(cfg):
        # concrete dummy data, same signature; the returned (f, x) pair
        # chains fwd+bwd inside autotune's one-dispatch timing loop
        # (grad(loss)(q) is q-shaped, so y = f(y) composes)
        rs = np.random.RandomState(0)
        hk = h_kv or h
        qv = jnp.asarray(rs.randn(b, sq, h, d), dtype)
        kv = jnp.asarray(rs.randn(b, sk, hk, d), dtype)
        vv = jnp.asarray(rs.randn(b, sk, hk, d), dtype)

        if biased:  # benchmark the kernel that will actually run
            bias_v = jnp.zeros((1, 1, sq, sk), jnp.float32)

            def loss(qv):
                return _flash_core_b(qv, kv, vv, bias_v, causal, cfg[0],
                                     cfg[1]).astype(jnp.float32).sum()
        else:
            # per-layout signatures time the layout's OWN core — caching
            # transpose-core timings under a kv/flat key would be the
            # same silent mismatch the layout tag exists to prevent
            core = {"kv": _flash_core_kv, "flat": _flash_core_flat,
                    "mh": _flash_core_mh}.get(lt, _flash_core)

            def loss(qv):
                return core(qv, kv, vv, causal, cfg[0],
                            cfg[1]).astype(jnp.float32).sum()

        return jax.grad(loss), qv

    sig = (f"{b}x{sq}x{sk}x{h}x{d}|{jnp.dtype(dtype).name}|c{int(causal)}"
           + (f"|kv{h_kv}" if h_kv and h_kv != h else "")
           + ("|bias" if biased else ""))
    if lt:
        # layout-tagged signature; a transpose-tuned winner for the same
        # shape is NOT reused (it was measured on different kernels) —
        # count the refusal so cold layout caches are visible
        lsig = sig + f"|L{lt}"
        if autotune.cached_config("flash_fwdbwd", lsig) is None and \
                autotune.cached_config("flash_fwdbwd", sig) is not None:
            _metrics.inc("autotune.cross_layout_reject", layout=lt)
            _flight.record("autotune.cross_layout_reject", layout=lt,
                           signature=sig)
        sig = lsig
    return autotune.pick("flash_fwdbwd", sig, cands, run, default)


def flash_attention_fwd(q, k, v, mask=None, is_causal=False,
                        block_q=None, block_k=None,
                        bias_grad_safe=False):
    """[B, S, H, D] in/out. Pallas kernel for causal/full. Block sizes
    are autotuned per signature unless passed explicitly. Odd sequence
    lengths (ViT's 197, ragged batches) run zero-padded to a multiple of
    8 with real-length masking inside the kernels — padded keys never
    contribute, padded query rows are sliced off (gradients included,
    via the custom VJP's real-length bounds).

    Masks: with bias_grad_safe=True (the caller vouches the mask needs
    no gradient — scaled_dot_product_attention checks stop_gradient),
    additive/boolean masks stream blockwise through the biased kernels
    ([Sq, Sk] scores never materialize); otherwise the fused-softmax
    reference path runs."""
    if mask is not None:
        if not (flash_attention_available(q) and bias_grad_safe
                and _biased_flash_ok(q, k, mask)):
            _metrics.inc("flash.dispatch", tier="fallback")
            _metrics.inc("flash.fallback_reason", reason="biased_gate")
            return _ref_attention(q, k, v, mask, is_causal)
        bias = mask
        if bias.dtype == jnp.bool_:
            bias = jnp.where(bias, 0.0, NEG_INF)
        bias = bias.astype(jnp.float32)
        if block_q is None or block_k is None:
            bq, bk = _tuned_blocks(q.shape[0], q.shape[1], k.shape[1],
                                   q.shape[2], q.shape[3], q.dtype,
                                   bool(is_causal), h_kv=k.shape[2],
                                   biased=True)
            block_q = block_q or bq
            block_k = block_k or bk
        # validate the FINAL block_k (after _pick_block shrinking): the
        # dKV bias band's trailing block dim must tile to 128 or equal sk
        sk_arr = k.shape[1]
        final_bk = _pick_block(sk_arr, block_k)
        if final_bk % 128 != 0 and final_bk != sk_arr:
            _gate_reject("biased", "bias_block_k", q, k,
                         (block_q, final_bk))
            _metrics.inc("flash.dispatch", tier="fallback")
            _metrics.inc("flash.fallback_reason", reason="bias_block_k")
            return _ref_attention(q, k, v, mask, is_causal)
        _metrics.inc("flash.dispatch", tier="biased")
        return _flash_core_b(q, k, v, bias, bool(is_causal), block_q,
                             final_bk)
    if not flash_attention_available(q):
        _metrics.inc("flash.dispatch", tier="fallback")
        _metrics.inc("flash.fallback_reason", reason="unavailable")
        return _ref_attention(q, k, v, mask, is_causal)
    if k.shape[2] != q.shape[2]:
        # GQA feasibility: the grouped dK/dV kernel keeps a KV head's
        # whole query group (rep x seq_q x d of q, o, do) resident in
        # VMEM; past the budget, fall back to expanded-KV MHA kernels
        # (correct, just without the KV-traffic saving) rather than
        # compile an infeasible kernel
        rep = q.shape[2] // k.shape[2]
        group_bytes = 3 * rep * q.shape[1] * q.shape[3] * q.dtype.itemsize
        # FLAGS_flash_gqa_expand: operator escape hatch — the round-5
        # on-chip A/B (chip_session gqa_ab) measured grouped winning
        # forward (1.6x at B4 S2048 32q/8kv D128) but LOSING backward at
        # 512x512 blocks (4.06 vs 2.87 ms), so the best choice is
        # shape-dependent; grouped (less KV HBM traffic) stays the
        # default
        from ...core import flags as _flags

        if _flags.get_flags(["FLAGS_flash_gqa_expand"])[
                "FLAGS_flash_gqa_expand"] or \
                group_bytes > 8 * 1024 * 1024:
            q, k, v = _expand_gqa_kv(q, k, v)
    sq, sk = q.shape[1], k.shape[1]
    pad_q = (-sq) % 8
    pad_k = (-sk) % 8
    if pad_q or pad_k:
        widths = lambda p: ((0, 0), (0, p), (0, 0), (0, 0))
        q = jnp.pad(q, widths(pad_q))
        k = jnp.pad(k, widths(pad_k))
        v = jnp.pad(v, widths(pad_k))
    # tier intent from the layout flag (before block tuning: kv/flat/mh
    # blocks tune under their own layout-tagged autotune signature)
    layout = _layout_flag()
    if pad_q or pad_k:
        intended = "transpose"  # padded shapes run the transpose core
    elif layout == "mh" and k.shape[2] == q.shape[2]:
        intended = "mh"  # the mh core is MHA-only; GQA stays grouped
    elif layout in ("flat", "auto"):
        # block-independent flat gates run BEFORE layout-tagged tuning:
        # an off-gate shape must not launch an autotune search that
        # times (and on TPU, Mosaic-compiles) the flat core it can
        # never run (review finding on the r6 dispatch restructure)
        intended = "flat" if _flat_static_ok(q, k) else "transpose"
    elif layout == "kv":
        intended = "kv"
    else:
        intended = "transpose"

    user_bq, user_bk = block_q, block_k

    def _resolve(tag):
        bq, bk = _tuned_blocks(q.shape[0], q.shape[1], k.shape[1],
                               q.shape[2], q.shape[3], q.dtype,
                               bool(is_causal), h_kv=k.shape[2],
                               layout=tag)
        return (user_bq if user_bq is not None else bq,
                user_bk if user_bk is not None else bk)

    if user_bq is None or user_bk is None:
        block_q, block_k = _resolve(intended)
    if pad_q or pad_k:
        _metrics.inc("flash.dispatch", tier="transpose")
        out = _flash_core(q, k, v, bool(is_causal), block_q, block_k,
                          sq, sk)
        return out[:, :sq]
    if intended == "mh":
        _metrics.inc("flash.dispatch", tier="mh")
        return _flash_core_mh(q, k, v, bool(is_causal), block_q, block_k)
    # the VMEM gates estimate with the blocks that will REALLY run (the
    # tuned values above, resolved via _pick_block exactly as the kernels
    # resolve them) — advisor-medium r5; gate rejects fall back to the
    # transpose core with transpose-signature blocks
    if intended == "flat":
        # static gates already passed above; only the block-dependent
        # VMEM bound remains
        if _kv_native_ok(q, k, block_q, block_k, _gate="flat"):
            # flat-native: unpadded [B,S,H*D] views, zero transposes
            _metrics.inc("flash.dispatch", tier="flat")
            return _flash_core_flat(q, k, v, bool(is_causal), block_q,
                                    block_k)
        if user_bq is None or user_bk is None:
            block_q, block_k = _resolve("transpose")
    elif intended == "kv":
        if _kv_native_ok(q, k, block_q, block_k):
            # mixed layout: K/V/dK/dV never transpose (GQA-native via rep)
            _metrics.inc("flash.dispatch", tier="kv")
            return _flash_core_kv(q, k, v, bool(is_causal), block_q,
                                  block_k)
        if user_bq is None or user_bk is None:
            block_q, block_k = _resolve("transpose")
    _metrics.inc("flash.dispatch", tier="transpose")
    return _flash_core(q, k, v, bool(is_causal), block_q, block_k)
