"""Fused rotary position embedding — Pallas TPU kernel.

Role parity: `paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu`
(exposed as `incubate.nn.functional.fused_rotary_position_embedding`).

Design (TPU-first):
  * Elementwise rotate in one VMEM pass: out = x·cos + rotate_half(x)·sin
    (neox layout — the half-split rotation keeps lane access contiguous;
    the interleaved layout would stride lanes and falls back to jnp).
  * q/k/v share the same (cos, sin) phases, so one kernel instance per
    tensor; the grid walks (B·S) row-blocks with heads×dim resident.
  * Backward is the same kernel with the adjoint rotation
    (rotate_half^T(u) = concat(u2, −u1)) — a Pallas kernel both ways.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _interpret, _pick_block


def rope_available(x) -> bool:
    from ...core import flags

    if not flags.pallas_enabled("rope"):
        return False
    if x.ndim != 4:
        return False
    d = x.shape[-1]
    h = x.shape[-2]
    if d % 2 != 0 or (h * d) % 128 != 0:
        return False
    return not _interpret()


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, adjoint):
    x = x_ref[:].astype(jnp.float32)       # [br, H, D]
    cos = cos_ref[:].astype(jnp.float32)   # [br, D]
    sin = sin_ref[:].astype(jnp.float32)
    d = x.shape[-1]
    half = d // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    if not adjoint:
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        rot = jnp.concatenate([x2, -x1], axis=-1)
    out = x * cos[:, None, :] + rot * sin[:, None, :]
    o_ref[:] = out.astype(o_ref.dtype)


def _rope_call(x, cos, sin, adjoint, interpret=None):
    b, s, h, d = x.shape
    rows = b * s
    x2 = x.reshape(rows, h, d)
    # phases broadcast to [rows, d] (cos/sin come in as [B|1, S, 1, D])
    cos2 = jnp.broadcast_to(cos.reshape(cos.shape[0], s, d),
                            (b, s, d)).reshape(rows, d)
    sin2 = jnp.broadcast_to(sin.reshape(sin.shape[0], s, d),
                            (b, s, d)).reshape(rows, d)
    br = _pick_block(rows, max(8, min(512, (1 << 20) // (4 * h * d))))
    grid = (pl.cdiv(rows, br),)
    out = pl.pallas_call(
        functools.partial(_rope_kernel, adjoint=adjoint),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h, d), lambda r: (r, 0, 0)),
                  pl.BlockSpec((br, d), lambda r: (r, 0)),
                  pl.BlockSpec((br, d), lambda r: (r, 0))],
        out_specs=pl.BlockSpec((br, h, d), lambda r: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h, d), x.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(x2, cos2, sin2)
    return out.reshape(b, s, h, d)


@jax.custom_vjp
def rope_pallas(x, cos, sin):
    """x: [B,S,H,D]; cos/sin: [B|1, S, 1, D] neox-layout phases."""
    return _rope_call(x, cos, sin, adjoint=False)


def _rope_fwd(x, cos, sin):
    return _rope_call(x, cos, sin, adjoint=False), (cos, sin)


def _rope_bwd(saved, g):
    cos, sin = saved
    return _rope_call(g, cos, sin, adjoint=True), None, None


rope_pallas.defvjp(_rope_fwd, _rope_bwd)
