"""Fused Swin window attention — Pallas TPU kernel.

Role parity: the window-attention fusion the reference ecosystem gets
from its fused attention stack; here it is the ISSUE-10 answer to the
PERF.md round-5 Swin ablation, which put the windowed-attention
machinery (cyclic roll + 6-D window-partition transposes + rel-pos-bias
gather + reverse) at ~43% of achievable Swin-T step rate.

Design (TPU-first):
  * ONE kernel owns the whole windowed-attention block: cyclic shift
    (static-rotate concat of two slices — the shift is a Python int),
    window partition (static slices of the image block — the 6-D
    partition/reverse transposes never exist in the XLA program),
    per-head attention over [ws², hd] tiles with the dense precomputed
    rel-pos bias and the shift mask added to the f32 logits, softmax,
    and window reverse — the output block is assembled and stored in
    image layout.
  * Input is the POST-projection qkv image [B, H, W, 3C]: the qkv
    Linear is a per-token matmul, so projecting before partition is
    exactly equivalent to the reference order and lets the kernel read
    q/k/v as static lane slices of one block (the flat-layout idiom of
    flash_attention.py's [B,S,H*D] tier).
  * Windows are tiny (ws² = 49 tokens for Swin), so nothing streams:
    each grid cell holds a band of window rows in VMEM and walks its
    windows/heads in a static Python loop. The band height is the
    autotuned parameter (full image required when shift > 0 — the row
    roll crosses bands).
  * Backward is a second Pallas kernel over the full image: it replays
    the forward logits per window and emits dqkv in image layout plus a
    per-batch dbias partial ([B, heads, ws², ws²], summed outside — the
    rel-pos bias is trainable). The shift mask is stop-gradient by
    contract (zero cotangent).
  * Non-TPU backends run the same kernels through the Pallas
    interpreter in tests; the eager CPU fallback is the jnp reference
    below (`window_attention_ref`), which mirrors the kernel math
    op-for-op.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...observability import flight as _flight
from ...observability import metrics as _metrics
from .flash_attention import _interpret

__all__ = ["swin_window_attention", "window_attention_ref",
           "window_attention_available", "window_partition",
           "window_reverse"]

# VMEM feasibility bound for one grid cell (qkv band + out band + bias +
# mask + per-window f32 intermediates), conservative against the
# ~16 MiB/core default budget
_VMEM_BOUND = 8 * 1024 * 1024


# ========================= jnp reference =========================

def window_partition(x, ws):
    """[B, H, W, C] -> [B*nW, ws*ws, C] (row-major window order)."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // ws, ws, W // ws, ws, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(-1, ws * ws, C)


def window_reverse(windows, ws, H, W):
    """[B*nW, ws*ws, C] -> [B, H, W, C] — exact inverse of
    window_partition."""
    C = windows.shape[-1]
    B = windows.shape[0] // ((H // ws) * (W // ws))
    x = windows.reshape(B, H // ws, W // ws, ws, ws, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, H, W, C)


def _heads_attention(qkv_win, bias, mask_w, num_heads):
    """Shared per-window attention math on [N, P, 3C] window tokens —
    the single source of the numerics for the reference AND (via the
    same op order on 2-D tiles) the kernels. f32 logits/softmax,
    output in f32."""
    n, p, c3 = qkv_win.shape
    c = c3 // 3
    hd = c // num_heads
    scale = hd ** -0.5
    qkv_h = qkv_win.reshape(n, p, 3, num_heads, hd).astype(jnp.float32)
    q = qkv_h[:, :, 0].transpose(0, 2, 1, 3)        # [N, h, P, hd]
    k = qkv_h[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv_h[:, :, 2].transpose(0, 2, 1, 3)
    s = jnp.einsum("nhpd,nhqd->nhpq", q * scale, k) + bias[None]
    if mask_w is not None:
        nw = mask_w.shape[0]
        s = s.reshape(n // nw, nw, num_heads, p, p) + \
            mask_w[None, :, None].astype(jnp.float32)
        s = s.reshape(n, num_heads, p, p)
    pmax = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - pmax)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    out = jnp.einsum("nhpq,nhqd->nphd", probs, v)    # [N, P, h, hd]
    return out.reshape(n, p, c)


def window_attention_ref(qkv, bias, mask, *, window_size, shift,
                         num_heads):
    """jnp reference (the CPU dispatch fallback): identical semantics to
    the fused kernel — roll + partition + biased/masked attention +
    reverse + unroll. qkv: [B, H, W, 3C]; bias: [heads, ws², ws²] f32;
    mask: [nW, ws², ws²] additive or None. Returns [B, H, W, C]."""
    B, H, W, c3 = qkv.shape
    ws = window_size
    x = qkv
    if shift:
        x = jnp.roll(x, (-shift, -shift), axis=(1, 2))
    wins = window_partition(x, ws)                   # [B*nW, P, 3C]
    out = _heads_attention(wins, bias.astype(jnp.float32),
                           mask, num_heads)
    out = window_reverse(out.astype(qkv.dtype), ws, H, W)
    if shift:
        out = jnp.roll(out, (shift, shift), axis=(1, 2))
    return out


# ========================= Pallas kernels =========================

def _roll2(x, sh, sw):
    """Static cyclic rotate of the two leading (row, col) axes by python
    ints — two slice+concat pairs, no gather, no transpose."""
    if sh:
        sh = sh % x.shape[0]
        x = jnp.concatenate([x[sh:], x[:sh]], axis=0)
    if sw:
        sw = sw % x.shape[1]
        x = jnp.concatenate([x[:, sw:], x[:, :sw]], axis=1)
    return x


def _window_qkv_math(win, bias_ref, mask_ref, w_idx, num_heads):
    """One window's attention on a [P, 3C] tile, walking heads with
    static lane slices (the compile-proven flat idiom). Returns
    (out [P, C] f32, probs_per_head, q/k/v per head) — the extras feed
    the backward kernel's replay."""
    p, c3 = win.shape
    c = c3 // 3
    hd = c // num_heads
    scale = hd ** -0.5
    outs, probs, qs, ks, vs = [], [], [], [], []
    for h in range(num_heads):
        q = win[:, h * hd:(h + 1) * hd].astype(jnp.float32)
        k = win[:, c + h * hd:c + (h + 1) * hd].astype(jnp.float32)
        v = win[:, 2 * c + h * hd:2 * c + (h + 1) * hd].astype(
            jnp.float32)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s + bias_ref[h].astype(jnp.float32)
        if mask_ref is not None:
            s = s + mask_ref[w_idx].astype(jnp.float32)
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp(s - m)
        pr = e / jnp.sum(e, axis=-1, keepdims=True)
        o = jax.lax.dot_general(pr, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        outs.append(o)
        probs.append(pr)
        qs.append(q)
        ks.append(k)
        vs.append(v)
    return jnp.concatenate(outs, axis=-1), probs, qs, ks, vs


def _fwd_kernel(*refs, ws, shift, num_heads, n_wrows, has_mask):
    if has_mask:
        qkv_ref, bias_ref, mask_ref, o_ref = refs
    else:
        qkv_ref, bias_ref, o_ref = refs
        mask_ref = None
    x = qkv_ref[:]                                   # [rows, W, 3C]
    if shift:
        x = _roll2(x, shift, shift)
    W = x.shape[1]
    n_wcols = W // ws
    p = ws * ws
    row_bands = []
    for wi in range(n_wrows):
        row_out = []
        for wj in range(n_wcols):
            win = x[wi * ws:(wi + 1) * ws,
                    wj * ws:(wj + 1) * ws, :].reshape(p, -1)
            out, _, _, _, _ = _window_qkv_math(
                win, bias_ref, mask_ref, wi * n_wcols + wj, num_heads)
            row_out.append(out.reshape(ws, ws, -1))
        row_bands.append(jnp.concatenate(row_out, axis=1))
    img = jnp.concatenate(row_bands, axis=0)         # [rows, W, C]
    if shift:
        img = _roll2(img, -shift, -shift)
    o_ref[:] = img.astype(o_ref.dtype)


def _bwd_kernel(*refs, ws, shift, num_heads, n_wrows, has_mask):
    if has_mask:
        qkv_ref, bias_ref, mask_ref, g_ref, dqkv_ref, dbias_ref = refs
    else:
        qkv_ref, bias_ref, g_ref, dqkv_ref, dbias_ref = refs
        mask_ref = None
    x = qkv_ref[:]
    g = g_ref[:].astype(jnp.float32)
    if shift:
        x = _roll2(x, shift, shift)
        g = _roll2(g, shift, shift)
    W = x.shape[1]
    n_wcols = W // ws
    p = ws * ws
    c = x.shape[-1] // 3
    hd = c // num_heads
    scale = hd ** -0.5
    dbias = [jnp.zeros((p, p), jnp.float32) for _ in range(num_heads)]
    row_bands = []
    for wi in range(n_wrows):
        row_out = []
        for wj in range(n_wcols):
            win = x[wi * ws:(wi + 1) * ws,
                    wj * ws:(wj + 1) * ws, :].reshape(p, -1)
            gw = g[wi * ws:(wi + 1) * ws,
                   wj * ws:(wj + 1) * ws, :].reshape(p, c)
            _, probs, qs, ks, vs = _window_qkv_math(
                win, bias_ref, mask_ref, wi * n_wcols + wj, num_heads)
            parts = []
            for h in range(num_heads):
                gh = gw[:, h * hd:(h + 1) * hd]
                pr, q, k, v = probs[h], qs[h], ks[h], vs[h]
                dv = jax.lax.dot_general(
                    pr, gh, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                dp = jax.lax.dot_general(
                    gh, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                ds = pr * (dp - jnp.sum(dp * pr, axis=-1,
                                        keepdims=True))
                dq = jax.lax.dot_general(
                    ds, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                dk = jax.lax.dot_general(
                    ds, q, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                dbias[h] = dbias[h] + ds
                parts.append((dq, dk, dv))
            dwin = jnp.concatenate(
                [t[i] for i in range(3) for t in parts], axis=-1)
            row_out.append(dwin.reshape(ws, ws, 3 * c))
        row_bands.append(jnp.concatenate(row_out, axis=1))
    dimg = jnp.concatenate(row_bands, axis=0)
    if shift:
        dimg = _roll2(dimg, -shift, -shift)
    dqkv_ref[:] = dimg.astype(dqkv_ref.dtype)
    dbias_ref[:] = jnp.stack(dbias)


def _fwd_pallas(qkv, bias, mask, ws, shift, num_heads, band):
    """band = window rows per grid cell (== nWh for shifted blocks)."""
    B, H, W, c3 = qkv.shape
    c = c3 // 3
    n_wrows = H // ws
    has_mask = mask is not None
    rows = band * ws
    grid = (B, n_wrows // band)
    in_specs = [
        pl.BlockSpec((None, rows, W, c3), lambda bi, ri: (bi, ri, 0, 0)),
        pl.BlockSpec(bias.shape, lambda bi, ri: (0, 0, 0)),
    ]
    operands = [qkv, bias]
    if has_mask:
        in_specs.append(pl.BlockSpec(mask.shape,
                                     lambda bi, ri: (0, 0, 0)))
        operands.append(mask)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, ws=ws, shift=shift,
                          num_heads=num_heads, n_wrows=band,
                          has_mask=has_mask),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, rows, W, c),
                               lambda bi, ri: (bi, ri, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, W, c), qkv.dtype),
        interpret=_interpret(),
    )(*operands)


def _bwd_pallas(qkv, bias, mask, g, ws, shift, num_heads):
    """Full-image grid (B,): dbias partials are per-batch outputs summed
    by the caller — no cross-grid accumulation to serialize."""
    B, H, W, c3 = qkv.shape
    c = c3 // 3
    p = ws * ws
    n_wrows = H // ws
    has_mask = mask is not None
    in_specs = [
        pl.BlockSpec((None, H, W, c3), lambda bi: (bi, 0, 0, 0)),
        pl.BlockSpec(bias.shape, lambda bi: (0, 0, 0)),
    ]
    operands = [qkv, bias]
    if has_mask:
        in_specs.append(pl.BlockSpec(mask.shape, lambda bi: (0, 0, 0)))
        operands.append(mask)
    in_specs.append(pl.BlockSpec((None, H, W, c),
                                 lambda bi: (bi, 0, 0, 0)))
    operands.append(g)
    dqkv, dbias = pl.pallas_call(
        functools.partial(_bwd_kernel, ws=ws, shift=shift,
                          num_heads=num_heads, n_wrows=n_wrows,
                          has_mask=has_mask),
        grid=(B,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, H, W, c3), lambda bi: (bi, 0, 0, 0)),
            pl.BlockSpec((None, num_heads, p, p),
                         lambda bi: (bi, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, W, c3), qkv.dtype),
            jax.ShapeDtypeStruct((B, num_heads, p, p), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return dqkv, dbias.sum(axis=0)


# ===================== custom-vjp cores =====================
#
# custom_vjp needs a fixed positional signature, and the mask is
# optional — two specialized cores (with/without mask) keep None out of
# the differentiable arguments. The mask core gives the mask a zero
# cotangent by contract (swin shift masks are stop-gradient constants).

@functools.lru_cache(maxsize=None)
def _build_core(ws, shift, num_heads, band, has_mask):
    if has_mask:
        @jax.custom_vjp
        def core(qkv, bias, mask):
            return _fwd_pallas(qkv, bias, mask, ws, shift, num_heads,
                               band)

        def core_fwd(qkv, bias, mask):
            return core(qkv, bias, mask), (qkv, bias, mask)

        def core_bwd(res, g):
            qkv, bias, mask = res
            dqkv, dbias = _bwd_pallas(qkv, bias, mask, g, ws, shift,
                                      num_heads)
            return dqkv, dbias.astype(bias.dtype), jnp.zeros_like(mask)
    else:
        @jax.custom_vjp
        def core(qkv, bias):
            return _fwd_pallas(qkv, bias, None, ws, shift, num_heads,
                               band)

        def core_fwd(qkv, bias):
            return core(qkv, bias), (qkv, bias)

        def core_bwd(res, g):
            qkv, bias = res
            dqkv, dbias = _bwd_pallas(qkv, bias, None, g, ws, shift,
                                      num_heads)
            return dqkv, dbias.astype(bias.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


# ===================== dispatch =====================

def window_attention_available(qkv_shape, window_size, num_heads,
                               dtype_itemsize=4) -> bool:
    """Dispatch gate for the fused kernel: TPU backend, pallas tier
    enabled, window-tileable dims, and one full-image cell within the
    VMEM bound. Rejects surface through the flight recorder (the
    silent-fallback class of failure, ADVICE r5)."""
    from ...core import flags

    if not flags.pallas_enabled("window_attn"):
        return False
    if len(qkv_shape) != 4:
        return False
    B, H, W, c3 = qkv_shape
    ws = window_size
    if c3 % 3 or H % ws or W % ws:
        return False
    c = c3 // 3
    if c % num_heads:
        return False
    p = ws * ws
    # size for the WORST cell — the BACKWARD kernel's full-image cell,
    # which holds qkv + the cotangent + dqkv together (7c vs the
    # forward's 4c) plus bias, dbias partial, and the f32 per-window
    # logit/probs replays; a forward-only estimate admits shapes whose
    # training backward then fails the VMEM check at compile time
    est = (H * W * (2 * c3 + c) * dtype_itemsize
           + num_heads * p * p * 4 * 3 + 16 * p * p * 4)
    if est > _VMEM_BOUND:
        _metrics.inc("swin_attn.gate_reject", reason="vmem")
        _flight.record("swin_attn.gate_reject", reason="vmem",
                       qkv_shape=list(qkv_shape), est_bytes=est)
        return False
    return not _interpret()


def _tuned_band(qkv, ws, shift, num_heads, has_mask):
    """Autotuned window-row band per grid cell (existing autotune cache,
    `swin_window_attn` op). Shifted blocks need the full image (the row
    roll crosses bands), so only the shift-free case searches."""
    B, H, W, c3 = qkv.shape
    n_wrows = H // ws
    if shift or has_mask:
        return n_wrows
    cands = [b for b in (1, 2, 4, 8, n_wrows)
             if b <= n_wrows and n_wrows % b == 0]
    cands = sorted(set(cands))
    if len(cands) <= 1:
        return n_wrows
    from . import autotune

    def run(band):
        import numpy as np

        rs = np.random.RandomState(0)
        qv = jnp.asarray(rs.randn(*qkv.shape), qkv.dtype)
        bias = jnp.zeros((num_heads, ws * ws, ws * ws), jnp.float32)
        core = _build_core(ws, 0, num_heads, band, False)

        def loss(qv):
            return core(qv, bias).astype(jnp.float32).sum()

        # fwd+bwd chained (training is the Swin bench workload); grad
        # is qkv-shaped so the timing loop composes
        return jax.grad(loss), qv

    sig = (f"{B}x{H}x{W}x{c3}|ws{ws}|h{num_heads}"
           f"|{jnp.dtype(qkv.dtype).name}")
    return autotune.pick("swin_window_attn", sig, cands, run, n_wrows)


def swin_window_attention(qkv, bias, mask, *, window_size, shift,
                          num_heads):
    """Public fused window-attention entry (jax arrays in/out).

    qkv: [B, H, W, 3C] post-projection image; bias: dense
    [num_heads, ws², ws²] rel-pos bias (f32, trainable — receives a real
    gradient); mask: [nW, ws², ws²] additive shift mask or None
    (stop-gradient by contract). Returns [B, H, W, C].

    Dispatch: the Pallas kernel on TPU when the gate admits the shape
    (`swin_attn.dispatch{tier=pallas}`), the jnp reference elsewhere
    (`tier=fallback`) — the reference is the same math, so tests hold
    them together."""
    bias = bias.astype(jnp.float32)
    if window_attention_available(qkv.shape, window_size, num_heads,
                                  jnp.dtype(qkv.dtype).itemsize):
        band = _tuned_band(qkv, window_size, shift, num_heads,
                           mask is not None)
        core = _build_core(window_size, int(shift), num_heads, band,
                           mask is not None)
        _metrics.inc("swin_attn.dispatch", tier="pallas")
        if mask is not None:
            return core(qkv, bias, mask)
        return core(qkv, bias)
    _metrics.inc("swin_attn.dispatch", tier="fallback")
    return window_attention_ref(qkv, bias, mask, window_size=window_size,
                                shift=shift, num_heads=num_heads)
