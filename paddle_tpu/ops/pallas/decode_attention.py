"""Blocked KV-cache decode attention — Pallas TPU kernel.

Role parity: `paddle/phi/kernels/fusion/gpu/
masked_multihead_attention_kernel.cu` and
`block_multi_head_attention_kernel.cu` (exposed as
`incubate.nn.functional.masked_multihead_attention`).

Design (TPU-first):
  * One query token per (batch, head) grid cell attends over its KV cache
    with an online-softmax fori_loop over KV blocks — the loop bound is
    `ceil((pos+1)/block_k)` from a scalar-prefetched position vector, so
    a decode step costs O(tokens-in-cache), not O(cache-capacity). The
    jnp fallback attends the full fixed-size cache every step; this is
    the algorithmic win (plus: logits never hit HBM).
  * Shapes are static (cache capacity S), so the decode loop compiles
    once; only the scalar positions change step to step.
  * Inference-only (no VJP) — decode never backprops.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret, _pick_block, NEG_INF


def decode_attention_available(cache_shape) -> bool:
    from ...core import flags

    if not flags.pallas_enabled("decode"):
        return False
    _, b, h, s, d = cache_shape
    if d % 8 != 0 or d > 256 or s % 8 != 0:
        return False
    return not _interpret()


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, block_k, seq,
                   scale):
    bi = pl.program_id(0)
    pos = pos_ref[0, bi]                    # tokens start..pos are valid
    start = pos_ref[1, bi]                  # left-padded rows: start > 0
    q = q_ref[:].astype(jnp.float32) * scale        # [G, D]

    g = q.shape[0]                          # grouped queries per KV head
    d = q.shape[-1]
    # stats kept rank-2 (G, 1): rank-1 loop state does not lower through
    # Mosaic (same failure class as the round-2 flash LSE BlockSpec)
    m0 = jnp.full((g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    first = start // block_k                # skip fully-padded blocks
    num_iters = (pos + block_k) // block_k  # == cdiv(pos+1, block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [G,bk]
        k_ids = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (g, block_k), 1)
        s = jnp.where((k_ids >= start) & (k_ids <= pos), s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(first, num_iters, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention(q, kcache, vcache, pos, block_k=256, interpret=None,
                     start=None):
    """q: [B, Hq, D] current-token queries; kcache/vcache: [B, Hkv, S, D]
    (already containing the current token at index pos[b]); pos: [B] int32.
    start: optional [B] int32 — first valid cache index per row (> 0 for
    left-padded prompts; padding slots never contribute). Hq may be a
    multiple of Hkv (GQA): each KV head serves the Hq/Hkv-query group in
    one grid cell, so the cache is read ONCE per KV head — the bandwidth
    shape GQA exists for. Returns [B, Hq, D]."""
    b, hq, d = q.shape
    hkv = kcache.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of KV heads "
                         f"{hkv}")
    g = hq // hkv
    s = kcache.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_k = _pick_block(s, block_k)
    q4 = q.reshape(b, hkv, g, d)
    if start is None:
        start = jnp.zeros((b,), jnp.int32)
    pos2 = jnp.stack([pos.astype(jnp.int32),
                      start.astype(jnp.int32)])      # [2, B] scalar prefetch
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec((None, None, g, d), lambda bi, hi, pos_ref: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, pos_ref: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, pos_ref: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, g, d),
                               lambda bi, hi, pos_ref: (bi, hi, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_k=block_k, seq=s,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(pos2, q4, kcache, vcache)
    return out.reshape(b, hq, d)
