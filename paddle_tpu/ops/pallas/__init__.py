"""Pallas fused-kernel tier (role parity: `paddle/phi/kernels/fusion/gpu/`).

Kernels register here with jnp fallbacks so the same API works on CPU tests
and TPU. Heavy kernels live in sibling modules (flash_attention.py, ...).
"""
from __future__ import annotations

import jax

from .flash_attention import (  # noqa: F401
    flash_attention_available,
    flash_attention_fwd,
)
from .fused_norm import (  # noqa: F401
    fused_norm_available,
    fused_norm_pallas,
)
from .rope import rope_available, rope_pallas  # noqa: F401
from .decode_attention import (  # noqa: F401
    decode_attention,
    decode_attention_available,
)
from .paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_available,
    paged_attention_dispatch,
    paged_attention_reference,
)
from .window_attention import (  # noqa: F401
    swin_window_attention,
    window_attention_available,
    window_attention_ref,
)
from .conv_norm import (  # noqa: F401
    conv_bn_act_available,
    conv_bn_act_ref,
    fused_conv_bn_act,
)
