"""Pallas fused-kernel tier (role parity: `paddle/phi/kernels/fusion/gpu/`).

Kernels register here with jnp fallbacks so the same API works on CPU tests
and TPU. Heavy kernels live in sibling modules (flash_attention.py, ...).
"""
from __future__ import annotations

import jax

from .flash_attention import (  # noqa: F401
    flash_attention_available,
    flash_attention_fwd,
)
