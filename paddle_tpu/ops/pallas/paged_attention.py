"""Ragged paged-attention decode — Pallas TPU kernel + jnp reference.

Role parity: `block_multi_head_attention_kernel.cu`'s block-table decode
path (the reference's paged KV cache), in the style of *Ragged Paged
Attention* (PAPERS.md): each in-flight sequence keeps its KV state in
fixed-size pages drawn from a shared pool, addressed through a
per-sequence page table, with a per-sequence length — so one compiled
decode step serves a heterogeneous (ragged) batch without head-of-line
blocking on the longest request.

Design (TPU-first):
  * Grid ``(batch, kv_heads, pages)`` with the page table and positions
    scalar-prefetched: the KV BlockSpec index map reads
    ``page_table[b, p]`` to DMA each sequence's p-th page straight from
    the pool — the gather *is* the address computation, no materialized
    per-sequence contiguous cache ever exists.
  * Online softmax accumulates across the page grid axis in VMEM
    scratch (the flash pattern); pages entirely past a sequence's
    length are skipped with ``pl.when`` (compute cost is
    O(tokens-in-cache) per sequence, not O(pool capacity)).
  * Sequences shorter than the batch's longest simply run fewer page
    steps — raggedness costs masking, not padding to max length.
  * One query token per sequence slot; GQA groups ride the KV-head grid
    cell (the pool stores KV heads, read once per group).
  * Inference-only (no VJP) — decode never backprops.

Free slots in the engine's fixed batch point their page-table row at
page 0 (a reserved scratch page) with position 0: they compute one
masked page of garbage that the host discards — the compiled shape
never changes as sequences come and go.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret

__all__ = [
    "paged_attention", "paged_attention_reference",
    "paged_attention_available", "paged_attention_dispatch",
]


def paged_attention_available(pool_shape, pool_dtype=None) -> bool:
    """Can the Pallas kernel serve this pool shape on this backend?
    pool_shape: [num_pages, kv_heads, page_size, head_dim].  An int8
    pool (the quantized KV tier) additionally needs page_size to cover
    the int8 sublane tile (32) — smaller pages fall back to the jnp
    reference rather than fight the Mosaic layout."""
    from ...core import flags

    if not flags.pallas_enabled("paged"):
        return False
    _, _, ps, d = pool_shape
    if d % 8 != 0 or d > 256 or ps % 8 != 0:
        return False
    if pool_dtype is not None and jnp.dtype(pool_dtype) == jnp.int8 \
            and ps % 32 != 0:
        return False
    return not _interpret()


def _paged_kernel(sp_ref, q_ref, k_ref, v_ref, *refs, page_size,
                  block_k, scale, quantized):
    # quantized pools carry two extra inputs: the per-token-per-head
    # scale rows of this page (ks_ref/vs_ref, [page_size] each) —
    # dequantization happens HERE, on the VMEM-resident block, inside
    # the online-softmax accumulation (the pool stays int8 in HBM)
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = refs
    bi = pl.program_id(0)
    p = pl.program_id(2)
    npages = pl.num_programs(2)
    pos = sp_ref[bi, 0]                     # current token's index
    q = q_ref[:].astype(jnp.float32) * scale        # [G, D]
    g = q.shape[0]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    base = p * page_size

    @pl.when(base <= pos)                   # page holds >= 1 valid key
    def _compute():
        # valid keys within this page: indices [base, min(pos, base+ps-1)]
        valid = jnp.minimum(pos - base + 1, page_size)
        nblk = (valid + block_k - 1) // block_k

        def body(j, carry):
            m, l, acc = carry
            k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
            v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
            if quantized:
                k = k * ks_ref[pl.ds(j * block_k, block_k)][:, None]
                v = v * vs_ref[pl.ds(j * block_k, block_k)][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # [G, bk]
            k_ids = base + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (g, block_k), 1)
            s = jnp.where(k_ids <= pos, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            pexp = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(pexp, axis=1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                pexp, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(
            0, nblk, body, (m_ref[:], l_ref[:], acc_ref[:]))
        m_ref[:] = m
        l_ref[:] = l
        acc_ref[:] = acc

    @pl.when(p == npages - 1)
    def _finish():
        o_ref[:] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, page_table, pos, block_k=None,
                    interpret=None, k_scales=None, v_scales=None):
    """q: [B, Hq, D] current-token queries; k_pages/v_pages:
    [num_pages, Hkv, page_size, D] shared page pools (already containing
    each sequence's current token); page_table: [B, P] int32 page ids
    (unused tail entries must point at a reserved scratch page, e.g. 0);
    pos: [B] int32 — index of the current token per sequence (valid
    keys are exactly 0..pos[b]).  Hq may be a multiple of Hkv (GQA).

    Quantized KV tier (ISSUE 12): int8 pools with
    ``k_scales``/``v_scales`` [num_pages, Hkv, page_size] f32 — one
    scale per token vector per head, carried alongside the page table.
    The kernel interface is otherwise UNCHANGED (the Ragged Paged
    Attention design point): the same grid/BlockSpec gather also DMAs
    each page's scale row, and dequantization happens in VMEM inside
    the online-softmax accumulation, so page HBM traffic stays int8.
    Returns [B, Hq, D]."""
    b, hq, d = q.shape
    npool, hkv, ps, _ = k_pages.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of KV heads "
                         f"{hkv}")
    quantized = k_scales is not None
    if quantized != (v_scales is not None):
        raise ValueError("k_scales and v_scales must be given together")
    g = hq // hkv
    p = page_table.shape[1]
    scale = 1.0 / (d ** 0.5)
    if block_k is None:
        block_k = ps
    block_k = min(int(block_k), ps)
    if ps % block_k != 0:
        raise ValueError(f"block_k {block_k} must divide page_size {ps}")
    q4 = q.reshape(b, hkv, g, d)
    sp = jnp.concatenate(
        [pos.astype(jnp.int32)[:, None],
         page_table.astype(jnp.int32)], axis=1)         # [B, 1+P]

    def page_spec(bs3=None):
        # the ragged gather: this sequence's pi-th page, straight
        # from the pool (scratch page 0 for unused tail entries)
        if bs3 is None:
            return pl.BlockSpec((None, None, ps),
                                lambda bi, hi, pi, sp_ref:
                                (sp_ref[bi, pi + 1], hi, 0))
        return pl.BlockSpec((None, None, ps, bs3),
                            lambda bi, hi, pi, sp_ref:
                            (sp_ref[bi, pi + 1], hi, 0, 0))

    in_specs = [
        pl.BlockSpec((None, None, g, d),
                     lambda bi, hi, pi, sp_ref: (bi, hi, 0, 0)),
        page_spec(d),
        page_spec(d),
    ]
    inputs = [sp, q4, k_pages, v_pages]
    if quantized:
        in_specs += [page_spec(), page_spec()]
        inputs += [k_scales.astype(jnp.float32),
                   v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, p),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, g, d),
                               lambda bi, hi, pi, sp_ref: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    out_dtype = q.dtype
    out = pl.pallas_call(
        functools.partial(_paged_kernel, page_size=ps, block_k=block_k,
                          scale=scale, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), out_dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(*inputs)
    return out.reshape(b, hq, d)


def paged_attention_reference(q, k_pages, v_pages, page_table, pos,
                              k_scales=None, v_scales=None):
    """Dense jnp reference (and the CPU execution path): gather each
    sequence's pages into a contiguous view and attend with a masked
    softmax.  Numerically the plain-softmax twin of the kernel's online
    accumulation.  With scale tables (quantized int8 pools) each token
    vector dequantizes with its own per-head scale before the gather
    view — the same f32 multiply the kernel applies in VMEM."""
    from ..quant import dequantize_vectors

    b, hq, d = q.shape
    _, hkv, ps, _ = k_pages.shape
    p = page_table.shape[1]
    g = hq // hkv
    scale = 1.0 / (d ** 0.5)
    # gather FIRST, dequantize the gathered [B, P, ...] view: expanding
    # the whole pool to f32 before the gather would materialize 4x the
    # int8 pool bytes per decode step for pages nobody reads (same
    # values either way — dequant is an elementwise multiply)
    kg, vg = k_pages[page_table], v_pages[page_table]
    if k_scales is not None:
        kg = dequantize_vectors(kg, k_scales[page_table])
        vg = dequantize_vectors(vg, v_scales[page_table])
    # [B, P, Hkv, PS, D] -> [B, Hkv, P*PS, D]
    k = jnp.moveaxis(kg, 2, 1).reshape(b, hkv, p * ps, d)
    v = jnp.moveaxis(vg, 2, 1).reshape(b, hkv, p * ps, d)
    q4 = q.reshape(b, hkv, g, d).astype(jnp.float32) * scale
    s = jnp.einsum("bhgd,bhsd->bhgs", q4, k.astype(jnp.float32))
    ids = jnp.arange(p * ps, dtype=jnp.int32)
    mask = ids[None, :] <= pos.astype(jnp.int32)[:, None]   # [B, S]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgs,bhsd->bhgd", w, v.astype(jnp.float32))
    return out.reshape(b, hq, d).astype(q.dtype)


def _tuned_block_k(b, hq, d, dtype, pool_shape, n_tables,
                   pool_dtype="float32"):
    """Autotuned intra-page block_k for this paged-decode signature
    (cached per device kind on disk, like the flash/decode tiers).
    Candidates are page_size divisors ≥ 128 lanes-worth of rows — a
    sub-page block only helps when pages are large enough that the
    full-page score block pressures VMEM."""
    from . import autotune

    npool, hkv, ps, _ = pool_shape
    quantized = jnp.dtype(pool_dtype) == jnp.int8
    cands = []
    for c in (ps, 256, 128):
        c = min(c, ps)
        if ps % c == 0 and c % 8 == 0 and c not in cands:
            cands.append(c)
    if len(cands) <= 1:
        return ps
    sig = (f"b{b}h{hq}d{d}{dtype}|pool{npool}x{hkv}x{ps}"
           f"{pool_dtype}|pt{n_tables}")

    def run(cfg):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (b, hq, d), jnp.dtype(dtype))
        ks = vs = None
        if quantized:
            kp = jax.random.randint(kk, pool_shape, -127, 128,
                                    jnp.int8)
            vp = jax.random.randint(kv, pool_shape, -127, 128,
                                    jnp.int8)
            ks = jnp.ones(pool_shape[:3], jnp.float32)
            vs = jnp.ones(pool_shape[:3], jnp.float32)
        else:
            kp = jax.random.normal(kk, pool_shape, jnp.dtype(dtype))
            vp = jax.random.normal(kv, pool_shape, jnp.dtype(dtype))
        pt = jnp.tile(jnp.arange(n_tables, dtype=jnp.int32)[None, :],
                      (b, 1))
        pos = jnp.full((b,), n_tables * ps - 1, jnp.int32)

        def f(qq):
            return paged_attention(qq, kp, vp, pt, pos, block_k=cfg,
                                   k_scales=ks, v_scales=vs)

        return f, q

    return autotune.pick("paged_attention", sig, cands, run, default=ps)


def paged_attention_dispatch(q, k_pages, v_pages, page_table, pos,
                             k_scales=None, v_scales=None):
    """Dispatch-tier entry (the one the engine's decode program calls):
    the Pallas kernel when available (block_k autotuned per signature),
    the jnp reference otherwise.  Counts `paged.dispatch{tier=...}`.
    Scale tables route the quantized int8-pool tier through the SAME
    kernel (dequant in VMEM) or the same reference."""
    from ...observability import metrics as _metrics

    if paged_attention_available(k_pages.shape, k_pages.dtype):
        _metrics.inc("paged.dispatch", tier="pallas")
        block_k = _tuned_block_k(
            q.shape[0], q.shape[1], q.shape[2], str(q.dtype),
            tuple(k_pages.shape), page_table.shape[1],
            pool_dtype=str(k_pages.dtype))
        return paged_attention(q, k_pages, v_pages, page_table, pos,
                               block_k=block_k, k_scales=k_scales,
                               v_scales=v_scales)
    _metrics.inc("paged.dispatch", tier="fallback")
    return paged_attention_reference(q, k_pages, v_pages, page_table,
                                     pos, k_scales=k_scales,
                                     v_scales=v_scales)
