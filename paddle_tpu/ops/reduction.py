"""Reductions + search ops (paddle.tensor.{math,search,stat} parity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core import dtypes as _dtypes

_I64 = _dtypes.convert_dtype("int64")  # int32 when x64 is off (TPU default)

__all__ = [
    "sum", "mean", "max", "min", "prod", "amax", "amin", "nansum", "nanmean",
    "std", "var", "median", "nanmedian", "quantile", "all", "any",
    "argmax", "argmin", "count_nonzero", "mode", "kthvalue",
]


def _axes(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


@op("sum")
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dtype = _dtypes.convert_dtype(dtype)
    if dtype is None and jnp.issubdtype(x.dtype, jnp.bool_):
        dtype = _I64
    return jnp.sum(x, axis=_axes(axis), dtype=dtype, keepdims=keepdim)


@op("mean")
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axes(axis), keepdims=keepdim)


@op("max")
def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axes(axis), keepdims=keepdim)


@op("min")
def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axes(axis), keepdims=keepdim)


amax = max
amin = min


@op("prod")
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return jnp.prod(x, axis=_axes(axis), dtype=_dtypes.convert_dtype(dtype),
                    keepdims=keepdim)


@op("nansum")
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=_axes(axis), dtype=_dtypes.convert_dtype(dtype),
                      keepdims=keepdim)


@op("nanmean")
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axes(axis), keepdims=keepdim)


@op("std")
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=_axes(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op("var")
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=_axes(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op("median")
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    if mode == "avg":
        return jnp.median(x, axis=_axes(axis), keepdims=keepdim)
    # 'min' mode: lower of the two middle elements
    ax = -1 if axis is None else axis
    v = x.reshape(-1) if axis is None else x
    n = v.shape[ax]
    srt = jnp.sort(v, axis=ax)
    out = jnp.take(srt, (n - 1) // 2, axis=ax)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, ax)
    return out


@op("nanmedian")
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=_axes(axis), keepdims=keepdim)


@op("quantile")
def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.quantile(x, jnp.asarray(q), axis=_axes(axis), keepdims=keepdim,
                        method=interpolation)


@op("all")
def all(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=_axes(axis), keepdims=keepdim)


@op("any")
def any(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=_axes(axis), keepdims=keepdim)


@op("argmax")
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(_dtypes.convert_dtype(dtype))


@op("argmin")
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(_dtypes.convert_dtype(dtype))


@op("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_axes(axis), keepdims=keepdim).astype(_I64)


@op("mode")
def mode(x, axis=-1, keepdim=False, name=None):
    # most frequent value along axis; ties -> larger value (sorted scan)
    def mode1d(v):
        srt = jnp.sort(v)
        n = v.shape[0]
        idx = jnp.arange(n)
        # run-length: count of equal neighbors ending at i
        is_new = jnp.concatenate([jnp.array([True]), srt[1:] != srt[:-1]])
        run_id = jnp.cumsum(is_new) - 1
        counts = jax.ops.segment_sum(jnp.ones(n, jnp.int32), run_id, num_segments=n)
        best_run = jnp.argmax(counts)
        first_of_run = jnp.argmax(run_id == best_run)
        val = srt[first_of_run]
        orig_idx = jnp.max(jnp.where(v == val, idx, -1))
        return val, orig_idx.astype(_I64)

    moved = jnp.moveaxis(x, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals, idxs = jax.vmap(mode1d)(flat)
    vals = vals.reshape(moved.shape[:-1])
    idxs = idxs.reshape(moved.shape[:-1])
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idxs = jnp.expand_dims(idxs, axis)
    return vals, idxs


@op("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    srt = jnp.sort(x, axis=axis)
    arg = jnp.argsort(x, axis=axis)
    vals = jnp.take(srt, k - 1, axis=axis)
    idxs = jnp.take(arg, k - 1, axis=axis).astype(_I64)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idxs = jnp.expand_dims(idxs, axis)
    return vals, idxs
