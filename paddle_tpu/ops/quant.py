"""Shared symmetric-int8 codec — ONE definition for every int8 tier.

Three subsystems ride the same absmax→scale→round-to-nearest recipe
(ISSUE 12): the EQuARX quantized-collective wire tier
(`distributed/quantized.py`), the engine's weight-only decode
(`inference/engine`, per-output-channel scales), and the quantized KV
page pool (per-token-per-head vector scales carried next to the page
table).  Before this module each would have grown its own copy of the
scale/encode math, and a drift between any two silently changes either
the wire payload or the decode numerics — so the codec lives here once,
as pure jax-traceable functions with no framework deps, and everything
else imports it.

Codec contract (pinned by tests/test_quantized_decode.py):

* ``scales_from_absmax``: scale = absmax / 127, except an all-zero
  block clamps to scale 1 so quantized zeros stay exactly zero (never
  a 0/0 NaN).
* ``encode_int8``: symmetric round-to-nearest into [-127, 127]
  (jnp.round = round-half-to-even, the IEEE default).
* round-trip error per element is bounded by absmax/127 of its block —
  half a quantization step from rounding, and the bound the KV-pool
  error tests assert.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "CHUNK", "scales_from_absmax", "encode_int8", "decode_int8",
    "quantize_chunked", "dequantize_chunked", "quantize_channels",
    "dequantize_channels", "quantize_vectors", "dequantize_vectors",
]

# EQuARX uses hardware-convenient blocks; 256 keeps the scale sidecar
# under 0.4% of the payload while tracking local dynamic range.
CHUNK = 256


def scales_from_absmax(absmax):
    """Per-block scales from per-block absmax: a silent block (all
    zeros) must not divide by 0 — scale 1 keeps quantized zeros exactly
    zero.  THE one definition: the collective wire tier, the weight
    quantizer, and the KV pool must never drift."""
    return jnp.where(absmax > 0, absmax / 127.0, 1.0)


def encode_int8(x, scales):
    """Symmetric round-to-nearest int8 encode of ``x`` under
    broadcastable ``scales`` (counterpart of
    :func:`scales_from_absmax`).  Returns the clipped values still in
    the input float dtype — callers cast to int8 (or int32 for
    overflow-free accumulation) themselves."""
    return jnp.clip(jnp.round(x / scales), -127, 127)


def decode_int8(q, scales):
    """Inverse of :func:`encode_int8` back to f32 under broadcastable
    ``scales``."""
    return q.astype(jnp.float32) * scales


# ----------------------- chunked (wire payloads) -----------------------


def _as_chunks(x, chunk):
    """Flatten ``x`` to ``[n_chunks, chunk]`` (zero-padded tail);
    returns (chunks, pad)."""
    flat = x.reshape(-1)
    pad = (-flat.size) % chunk
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, chunk), pad


def quantize_chunked(x, chunk=CHUNK):
    """Symmetric per-chunk int8 quantization.  Returns
    ``(q_int8 [n_chunks, chunk], scales_f32 [n_chunks], pad)``."""
    ch, pad = _as_chunks(x.astype(jnp.float32), chunk)
    absmax = jnp.max(jnp.abs(ch), axis=1)
    scales = scales_from_absmax(absmax)
    q = encode_int8(ch, scales[:, None]).astype(jnp.int8)
    return q, scales, pad


def dequantize_chunked(q, scales, shape, pad):
    """Inverse of :func:`quantize_chunked` back to f32 ``shape``."""
    out = decode_int8(q, scales[:, None])
    flat = out.reshape(-1)
    if pad:
        flat = flat[:flat.size - pad]
    return flat.reshape(shape)


# ----------------------- per-channel (weights) -----------------------


def quantize_channels(w, axis=0):
    """Per-channel weight quantization: absmax reduced over ``axis``
    (the contraction dim), one scale per remaining channel.  Returns
    ``(q int8 (w.shape), scales f32 broadcastable to w.shape)`` — the
    scales keep a size-1 dim where the reduction happened, so
    ``decode_int8(q, scales)`` needs no axis bookkeeping."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scales = scales_from_absmax(absmax)
    q = encode_int8(w32, scales).astype(jnp.int8)
    return q, scales


def dequantize_channels(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_channels` into ``dtype``.  The
    multiply runs in f32 and casts once — the same value every tier
    produces for the same (q, scale)."""
    return decode_int8(q, scales).astype(dtype)


# ----------------------- per-vector (KV pages) -----------------------


def quantize_vectors(x):
    """Quantize the trailing dim of ``x`` as independent vectors: one
    scale per leading index (a KV head vector per token gets its own
    absmax, so page writes never require requantizing neighbours).
    Returns ``(q int8 (x.shape), scales f32 x.shape[:-1])``."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    scales = scales_from_absmax(absmax)
    q = encode_int8(x32, scales[..., None]).astype(jnp.int8)
    return q, scales


def dequantize_vectors(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_vectors` into ``dtype``."""
    return decode_int8(q, scales[..., None]).astype(dtype)
