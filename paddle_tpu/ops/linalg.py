"""Linear algebra ops (paddle.tensor.linalg parity:
`python/paddle/tensor/linalg.py`; kernels land on the MXU via XLA dot/conv)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtypes as _dtypes

from ..core.dispatch import op
from ..core.tensor import Tensor

_I64 = _dtypes.convert_dtype("int64")  # int32 when x64 is off (TPU default)

__all__ = [
    "matmul", "dot", "bmm", "mv", "t", "norm", "dist", "einsum", "cross",
    "cholesky", "cholesky_solve", "qr", "svd", "pca_lowrank", "matrix_rank",
    "inverse", "pinv", "solve", "triangular_solve", "lstsq", "lu", "lu_unpack",
    "eig", "eigh", "eigvals", "eigvalsh", "slogdet", "det", "matrix_power",
    "multi_dot", "histogram", "histogramdd", "bincount", "cov", "corrcoef",
    "cdist", "householder_product", "matrix_exp", "vander", "vecdot",
    "cond_number", "svdvals", "vector_norm", "matrix_norm", "ormqr",
]


@op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if jnp.ndim(x) > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if jnp.ndim(y) > 1 else y
    return jnp.matmul(x, y)


@op("dot")
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@op("bmm")
def bmm(x, y, name=None):
    return jax.lax.batch_matmul(x, y)


@op("mv")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@op("t")
def t(x, name=None):
    if jnp.ndim(x) < 2:
        return x
    return jnp.swapaxes(x, 0, 1)


@op("norm")
def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=axis, keepdims=keepdim))
    if p == "nuc":
        s = jnp.linalg.svd(x, compute_uv=False)
        return jnp.sum(s, axis=-1, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@op("dist")
def dist(x, y, p=2, name=None):
    d = jnp.abs(x - y)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    return jnp.sum(d ** p) ** (1.0 / p)


@op("einsum")
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


@op("cross")
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return jnp.cross(x, y, axis=axis)


@op("cholesky")
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@op("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@op("qr")
def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


@op("svd")
def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2).conj()


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = v.shape[-2], v.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        v = v - jnp.mean(v, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(v, full_matrices=False)
    return Tensor(u[..., :q]), Tensor(s[..., :q]), \
        Tensor(jnp.swapaxes(vt, -1, -2)[..., :q])


@op("matrix_rank")
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol).astype(_I64)


@op("inverse")
def inverse(x, name=None):
    return jnp.linalg.inv(x)


@op("pinv")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@op("solve")
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    sol, res, rank_, sv = jnp.linalg.lstsq(xv, yv, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank_.astype(_I64)),
            Tensor(sv))


@op("lu")
def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    piv = piv.astype(jnp.int32) + 1  # 1-based like the reference kernel
    if get_infos:
        info = jnp.zeros(x.shape[:-2], jnp.int32)
        return lu_, piv, info
    return lu_, piv


@op("lu_unpack")
def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    piv = lu_pivots.astype(jnp.int32) - 1
    perm = jnp.arange(m, dtype=jnp.int32)

    def body(i, p):
        a, b = p[i], p[piv[i]]
        return p.at[i].set(b).at[piv[i]].set(a)

    perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
    P = jnp.eye(m, dtype=lu_data.dtype)[perm].T
    return P, L, U


def eig(x, name=None):
    # general eig is CPU-only in XLA; host round-trip like reference's LAPACK call
    import numpy as np

    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    w, vec = np.linalg.eig(v)
    return Tensor(w), Tensor(vec)


@op("eigh")
def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(x, symmetrize_input=True)
    return w, v


def eigvals(x, name=None):
    import numpy as np

    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    return Tensor(np.linalg.eigvals(v))


@op("eigvalsh")
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x)


@op("slogdet")
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@op("det")
def det(x, name=None):
    return jnp.linalg.det(x)


@op("matrix_power")
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@op("multi_dot")
def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(list(x))


@op("histogram")
def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    if min == 0 and max == 0:
        lo, hi = jnp.min(input), jnp.max(input)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(input.reshape(-1), bins=bins, range=(lo, hi),
                            weights=weight, density=density)
    return hist if (density or weight is not None) else hist.astype(_I64)


@op("histogramdd")
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    if ranges is not None:
        # reference API: flat [lo0, hi0, lo1, hi1, ...]
        import numpy as _np

        flat = _np.asarray(ranges, float).reshape(-1, 2)
        ranges = [tuple(p) for p in flat]
    return jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                           weights=weights)


@op("bincount")
def bincount(x, weights=None, minlength=0, name=None):
    length = max(minlength, 1)
    out = jnp.bincount(x.reshape(-1), weights=weights,
                       minlength=minlength,
                       length=None)
    return out


@op("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@op("corrcoef")
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@op("cdist")
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    d = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(d), axis=-1)
    return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)


@op("householder_product")
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]

    def single(a, t):
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, a[:, i]))
            h = jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v)
            q = q @ h
        return q

    if x.ndim == 2:
        return single(x, tau)[:, :n]
    flat_x = x.reshape((-1,) + x.shape[-2:])
    flat_t = tau.reshape((-1,) + tau.shape[-1:])
    out = jax.vmap(single)(flat_x, flat_t)[..., :, :n]
    return out.reshape(x.shape[:-2] + (m, n))


@op("matrix_exp")
def matrix_exp(x, name=None):
    return jax.scipy.linalg.expm(x)


@op("vander")
def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(x, N=n, increasing=increasing)


@op("vecdot")
def vecdot(x, y, axis=-1, name=None):
    return jnp.sum(x * y, axis=axis)


@op("cond")
def cond_number(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@op("svdvals")
def svdvals(x, name=None):
    return jnp.linalg.svd(x, compute_uv=False)


@op("vector_norm")
def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jnp.linalg.vector_norm(x, ord=p, axis=axis, keepdims=keepdim)


@op("matrix_norm")
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    axis = tuple(a % x.ndim for a in axis)
    moved = axis != (x.ndim - 2, x.ndim - 1)
    if moved:
        x = jnp.moveaxis(x, axis, (-2, -1))
    out = jnp.linalg.matrix_norm(x, ord=p, keepdims=keepdim)
    if moved and keepdim:
        out = jnp.moveaxis(out, (-2, -1), axis)
    return out


@op("ormqr")
def ormqr(x, tau, other, left=True, transpose=False, name=None):
    # Q from householder reflectors (geqrf layout), then Q@other / other@Q
    m = x.shape[-2]
    n = tau.shape[-1]

    def build_q(a, t):
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, a[:, i]))
            h = jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v)
            q = q @ h
        return q

    if x.ndim == 2:
        q = build_q(x, tau)
    else:
        flat_x = x.reshape((-1,) + x.shape[-2:])
        flat_t = tau.reshape((-1,) + tau.shape[-1:])
        q = jax.vmap(build_q)(flat_x, flat_t).reshape(
            x.shape[:-2] + (m, m))
    if transpose:
        q = jnp.swapaxes(q, -1, -2)
    return jnp.matmul(q, other) if left else jnp.matmul(other, q)
