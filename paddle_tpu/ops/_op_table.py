"""AUTO-GENERATED from OPS_MANIFEST.json by
tools/gen_op_manifest.py --emit.  DO NOT EDIT BY HAND —
regenerate with:  python tools/gen_op_manifest.py --emit

Generated op table (`ops.yaml` generator role): the public op
surface, Tensor-method set, grad-checked set, and inplace pairs,
emitted FROM the manifest so the schema is the single source of
truth in both directions (tests/test_manifest_ops.py).
"""

# op name -> namespace that must resolve it
PUBLIC_OPS = {
    "paddle_tpu": (
        "abs", "abs_", "accuracy", "acos", "acos_", "acosh", "acosh_", "add",
        "add_", "add_n", "addmm", "addmm_", "all", "allclose", "amax",
        "amin", "angle", "any", "arange", "argmax", "argmin", "argsort",
        "as_complex", "as_real", "as_strided", "asin", "asin_", "asinh",
        "asinh_", "assign", "atan", "atan2", "atan_", "atanh", "atanh_",
        "atleast_1d", "atleast_2d", "atleast_3d", "auc", "bernoulli",
        "bincount", "binomial", "bitwise_and", "bitwise_and_", "bitwise_not",
        "bitwise_not_", "bitwise_or", "bitwise_or_", "bitwise_xor",
        "bitwise_xor_", "bmm", "broadcast_shape", "broadcast_tensors",
        "broadcast_to", "bucketize", "cast", "cast_", "cauchy_", "cdist",
        "ceil", "ceil_", "cholesky", "cholesky_solve", "chunk", "clip",
        "clip_", "clip_by_norm", "combinations", "complex", "concat", "conj",
        "corrcoef", "cos", "cos_", "cosh", "cosh_", "count_nonzero", "cov",
        "create_parameter", "create_tensor", "crop", "cross", "cummax",
        "cummin", "cumprod", "cumprod_", "cumsum", "cumsum_",
        "cumulative_trapezoid", "deg2rad", "det", "diag", "diag_embed",
        "diagflat", "diagonal", "diagonal_scatter", "diff", "digamma",
        "digamma_", "dirichlet", "dist", "divide", "divide_", "dot",
        "dsplit", "edit_distance", "eig", "eigh", "eigvals", "eigvalsh",
        "einsum", "empty", "empty_like", "equal", "equal_", "equal_all",
        "erf", "erfinv", "erfinv_", "exp", "exp_", "expand", "expand_as",
        "expm1", "exponential_", "eye", "fill", "fill_diagonal",
        "fill_diagonal_tensor", "flatten", "flatten_", "flip", "floor",
        "floor_", "floor_divide", "floor_divide_", "floor_mod", "floor_mod_",
        "fmax", "fmin", "frac", "frac_", "frexp", "full", "full_like",
        "gammaln", "gammaln_", "gather", "gather_nd", "gather_tree",
        "gaussian", "gcd", "gcd_", "geometric_", "greater_equal",
        "greater_equal_", "greater_than", "greater_than_", "heaviside",
        "histogram", "histogramdd", "householder_product", "hsplit", "hypot",
        "hypot_", "i0", "i0_", "i0e", "i1", "i1e", "identity_loss", "imag",
        "increment", "index_add", "index_add_", "index_fill", "index_fill_",
        "index_put", "index_put_", "index_sample", "index_select", "inner",
        "inverse", "is_complex", "is_empty", "is_floating_point",
        "is_integer", "is_tensor", "isclose", "isfinite", "isinf", "isnan",
        "kron", "kthvalue", "lcm", "lcm_", "ldexp", "ldexp_", "lerp",
        "lerp_", "less_equal", "less_equal_", "less_than", "less_than_",
        "lgamma", "lgamma_", "linspace", "log", "log10", "log10_", "log1p",
        "log1p_", "log2", "log2_", "log_", "logaddexp", "logcumsumexp",
        "logical_and", "logical_and_", "logical_not", "logical_not_",
        "logical_or", "logical_or_", "logical_xor", "logical_xor_", "logit",
        "logit_", "logspace", "logsumexp", "lstsq", "lu", "lu_unpack",
        "masked_fill", "masked_fill_", "masked_scatter", "masked_scatter_",
        "masked_select", "matmul", "matrix_power", "matrix_rank", "max",
        "maximum", "mean", "median", "meshgrid", "min", "minimum", "mm",
        "mod", "mod_", "mode", "moveaxis", "multi_dot", "multigammaln",
        "multigammaln_", "multinomial", "multiplex", "multiply", "multiply_",
        "mv", "nan_to_num", "nan_to_num_", "nanmean", "nanmedian",
        "nanquantile", "nansum", "neg", "neg_", "nextafter", "nonzero",
        "norm", "normal_", "not_equal", "not_equal_", "numel", "one_hot",
        "ones", "ones_like", "outer", "pad", "pca_lowrank", "pinv",
        "poisson", "polar", "polygamma", "polygamma_", "pow", "pow_", "prod",
        "put_along_axis", "put_along_axis_", "qr", "quantile", "rad2deg",
        "randint", "randperm", "rank", "real", "reciprocal", "reciprocal_",
        "remainder", "remainder_", "renorm", "renorm_", "repeat_interleave",
        "reshape", "reshape_", "reverse", "roll", "rot90", "round", "round_",
        "rsqrt", "rsqrt_", "scale", "scale_", "scatter", "scatter_",
        "scatter_nd", "scatter_nd_add", "searchsorted", "select_scatter",
        "sgn", "shape", "shard_index", "sigmoid", "sigmoid_", "sign",
        "signbit", "sin", "sin_", "sinh", "sinh_", "slice", "slice_scatter",
        "slogdet", "solve", "sort", "split", "split_with_num", "sqrt",
        "sqrt_", "square", "squeeze", "squeeze_", "stack", "standard_gamma",
        "stanh", "std", "strided_slice", "subtract", "subtract_", "sum",
        "svd", "t", "t_", "take", "take_along_axis", "tan", "tan_", "tanh",
        "tanh_", "temporal_shift", "tensor_split", "tensordot", "tile",
        "top_p_sampling", "topk", "trace", "transpose", "transpose_",
        "trapezoid", "triangular_solve", "tril", "tril_", "tril_indices",
        "triu", "triu_", "triu_indices", "trunc", "trunc_", "unbind",
        "unflatten", "unfold", "uniform", "uniform_", "unique",
        "unique_consecutive", "unsqueeze", "unsqueeze_", "unstack", "vander",
        "var", "view", "view_as", "viterbi_decode", "vsplit", "where",
        "where_", "zeros", "zeros_like",
    ),
    "paddle_tpu.geometric": (
        "reindex_graph", "send_u_recv", "send_ue_recv", "send_uv",
        "weighted_sample_neighbors",
    ),
    "paddle_tpu.linalg": (
        "cond",
    ),
    "paddle_tpu.nn.functional": (
        "affine_grid", "batch_norm", "bilinear", "celu", "channel_shuffle",
        "class_center_sample", "conv2d", "conv2d_transpose", "conv3d",
        "conv3d_transpose", "dropout", "elu", "embedding",
        "flash_attn_unpadded", "fold", "gelu", "grid_sample", "group_norm",
        "gumbel_softmax", "hardshrink", "hardsigmoid", "hardswish",
        "hardtanh", "hsigmoid_loss", "instance_norm", "label_smooth",
        "layer_norm", "leaky_relu", "log_loss", "log_softmax",
        "margin_cross_entropy", "maxout", "mish", "nll_loss",
        "pixel_shuffle", "pixel_unshuffle", "prelu", "relu", "relu6",
        "rms_norm", "rrelu", "selu", "sequence_mask", "silu", "softmax",
        "softplus", "softshrink", "softsign", "swish", "thresholded_relu",
    ),
    "paddle_tpu.nn.quant": (
        "llm_int8_linear", "weight_dequantize", "weight_only_linear",
        "weight_quantize",
    ),
    "paddle_tpu.signal": (
        "frame", "istft", "overlap_add", "stft",
    ),
    "paddle_tpu.vision.ops": (
        "box_coder", "decode_jpeg", "distribute_fpn_proposals",
        "generate_proposals", "matrix_nms", "nms", "prior_box", "psroi_pool",
        "read_file", "roi_align", "roi_pool", "yolo_box", "yolo_loss",
    ),
}

TENSOR_METHODS = (
    "abs", "abs_", "acos", "acos_", "acosh", "acosh_", "add", "add_",
    "add_n", "addmm", "addmm_", "all", "allclose", "amax", "amin", "angle",
    "any", "argmax", "argmin", "argsort", "as_complex", "as_real",
    "as_strided", "asin", "asin_", "asinh", "asinh_", "assign", "atan",
    "atan2", "atan_", "atanh", "atanh_", "atleast_1d", "atleast_2d",
    "atleast_3d", "auc", "bernoulli", "bincount", "binomial", "bitwise_and",
    "bitwise_and_", "bitwise_not", "bitwise_not_", "bitwise_or",
    "bitwise_or_", "bitwise_xor", "bitwise_xor_", "bmm", "broadcast_shape",
    "broadcast_tensors", "broadcast_to", "bucketize", "cast", "cast_",
    "cauchy_", "cdist", "ceil", "ceil_", "cholesky", "cholesky_solve",
    "chunk", "clip", "clip_", "clip_by_norm", "combinations", "complex",
    "concat", "cond", "conj", "corrcoef", "cos", "cos_", "cosh", "cosh_",
    "count_nonzero", "cov", "create_tensor", "crop", "cross", "cummax",
    "cummin", "cumprod", "cumprod_", "cumsum", "cumsum_",
    "cumulative_trapezoid", "deg2rad", "det", "diag", "diag_embed",
    "diagflat", "diagonal", "diagonal_scatter", "diff", "digamma",
    "digamma_", "dirichlet", "dist", "divide", "divide_", "dot", "dsplit",
    "edit_distance", "eig", "eigh", "eigvals", "eigvalsh", "einsum",
    "empty_like", "equal", "equal_", "equal_all", "erf", "erfinv", "erfinv_",
    "exp", "exp_", "expand", "expand_as", "expm1", "exponential_", "fill",
    "fill_diagonal", "fill_diagonal_tensor", "flatten", "flatten_", "flip",
    "floor", "floor_", "floor_divide", "floor_divide_", "floor_mod",
    "floor_mod_", "fmax", "fmin", "frac", "frac_", "frexp", "full_like",
    "gammaln", "gammaln_", "gather", "gather_nd", "gather_tree", "gaussian",
    "gcd", "gcd_", "geometric_", "greater_equal", "greater_equal_",
    "greater_than", "greater_than_", "heaviside", "histogram", "histogramdd",
    "householder_product", "hsplit", "hypot", "hypot_", "i0", "i0_", "i0e",
    "i1", "i1e", "identity_loss", "imag", "increment", "index_add",
    "index_add_", "index_fill", "index_fill_", "index_put", "index_put_",
    "index_sample", "index_select", "inner", "inverse", "is_complex",
    "is_empty", "is_floating_point", "is_integer", "is_tensor", "isclose",
    "isfinite", "isinf", "isnan", "istft", "kron", "kthvalue", "lcm", "lcm_",
    "ldexp", "ldexp_", "lerp", "lerp_", "less_equal", "less_equal_",
    "less_than", "less_than_", "lgamma", "lgamma_", "log", "log10", "log10_",
    "log1p", "log1p_", "log2", "log2_", "log_", "logaddexp", "logcumsumexp",
    "logical_and", "logical_and_", "logical_not", "logical_not_",
    "logical_or", "logical_or_", "logical_xor", "logical_xor_", "logit",
    "logit_", "logsumexp", "lstsq", "lu", "lu_unpack", "masked_fill",
    "masked_fill_", "masked_scatter", "masked_scatter_", "masked_select",
    "matmul", "matrix_power", "matrix_rank", "max", "maximum", "mean",
    "median", "min", "minimum", "mm", "mod", "mod_", "mode", "moveaxis",
    "multi_dot", "multigammaln", "multigammaln_", "multinomial", "multiplex",
    "multiply", "multiply_", "mv", "nan_to_num", "nan_to_num_", "nanmean",
    "nanmedian", "nanquantile", "nansum", "neg", "neg_", "nextafter",
    "nonzero", "norm", "normal_", "not_equal", "not_equal_", "numel",
    "one_hot", "ones_like", "outer", "pad", "pca_lowrank", "pinv", "poisson",
    "polar", "polygamma", "polygamma_", "pow", "pow_", "prod",
    "put_along_axis", "put_along_axis_", "qr", "quantile", "rad2deg", "rank",
    "real", "reciprocal", "reciprocal_", "remainder", "remainder_", "renorm",
    "renorm_", "repeat_interleave", "reshape", "reshape_", "reverse", "roll",
    "rot90", "round", "round_", "rsqrt", "rsqrt_", "scale", "scale_",
    "scatter", "scatter_", "scatter_nd", "scatter_nd_add", "searchsorted",
    "select_scatter", "sgn", "shape", "shard_index", "sigmoid", "sigmoid_",
    "sign", "signbit", "sin", "sin_", "sinh", "sinh_", "slice",
    "slice_scatter", "slogdet", "solve", "sort", "split", "split_with_num",
    "sqrt", "sqrt_", "square", "squeeze", "squeeze_", "stack",
    "standard_gamma", "stanh", "std", "stft", "strided_slice", "subtract",
    "subtract_", "sum", "svd", "t", "t_", "take", "take_along_axis", "tan",
    "tan_", "tanh", "tanh_", "temporal_shift", "tensor_split", "tensordot",
    "tile", "top_p_sampling", "topk", "trace", "transpose", "transpose_",
    "trapezoid", "triangular_solve", "tril", "tril_", "tril_indices", "triu",
    "triu_", "triu_indices", "trunc", "trunc_", "unbind", "unflatten",
    "unfold", "uniform_", "unique", "unique_consecutive", "unsqueeze",
    "unsqueeze_", "unstack", "vander", "var", "view", "view_as",
    "viterbi_decode", "vsplit", "where", "where_", "zeros_like",
)

GRAD_CHECKED = (
    "abs", "acos", "acosh", "add", "asin", "asinh", "atan", "atan2", "atanh",
    "cos", "cosh", "digamma", "divide", "erf", "erfinv", "exp", "expm1",
    "fmax", "fmin", "gammaln", "hypot", "i0", "i0e", "i1", "i1e", "lerp",
    "lgamma", "log", "log10", "log1p", "log2", "logaddexp", "logit",
    "maximum", "minimum", "multiply", "neg", "pow", "reciprocal", "rsqrt",
    "sigmoid", "sin", "sinh", "sqrt", "square", "subtract", "tan", "tanh",
)

INPLACE_OPS = (
    "abs", "acos", "acosh", "add", "addmm", "asin", "asinh", "atan", "atanh",
    "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor", "cast",
    "ceil", "clip", "cos", "cosh", "cumprod", "cumsum", "digamma", "divide",
    "elu", "equal", "erf", "erfinv", "exp", "expm1", "fill", "fill_diagonal",
    "flatten", "floor", "floor_divide", "floor_mod", "frac", "gammaln",
    "gcd", "greater_equal", "greater_than", "hardtanh", "hypot", "i0",
    "index_add", "index_fill", "index_put", "lcm", "ldexp", "leaky_relu",
    "lerp", "less_equal", "less_than", "lgamma", "log", "log10", "log1p",
    "log2", "logical_and", "logical_not", "logical_or", "logical_xor",
    "logit", "masked_fill", "masked_scatter", "mod", "multigammaln",
    "multiply", "nan_to_num", "neg", "not_equal", "polygamma", "pow",
    "put_along_axis", "reciprocal", "relu", "remainder", "renorm", "reshape",
    "round", "rsqrt", "scale", "scatter", "sigmoid", "sin", "sinh",
    "softmax", "sqrt", "square", "squeeze", "subtract", "t", "tan", "tanh",
    "thresholded_relu", "transpose", "tril", "triu", "trunc", "uniform",
    "unsqueeze", "where",
)


def validate():
    """Resolve the generated surface against the live package;
    returns a list of violations (empty == green)."""
    import importlib

    problems = []
    for where, names in PUBLIC_OPS.items():
        mod = importlib.import_module(where)
        for n in names:
            if getattr(mod, n, None) is None:
                problems.append(f"{where}.{n} missing")
    from paddle_tpu.core.tensor import Tensor

    for n in TENSOR_METHODS:
        if not hasattr(Tensor, n):
            problems.append(f"Tensor.{n} missing")
    import paddle_tpu as P

    for n in INPLACE_OPS:
        t = n + '_'
        if (getattr(P, t, None) is None and not hasattr(Tensor, t)
                and getattr(P.nn.functional, t, None) is None):
            problems.append(f"inplace twin {t} missing")
    return problems
