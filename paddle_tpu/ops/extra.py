"""Long-tail tensor ops (manifest-closure batch).

Role parity: assorted `python/paddle/tensor/` ops (manipulation.py,
math.py, random.py) that round out the OPS_MANIFEST coverage — each op
maps to one jnp/lax expression; grads come from `jax.vjp` through the
dispatch gate like every other op.
"""
from __future__ import annotations

import itertools
import math as _math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, op
from ..core.tensor import Tensor


def _index_dtype():
    """Canonical `int64`-request dtype: int32 with x64 disabled (the
    documented TPU-first demotion, core/dtypes.py) — warning-free."""
    from ..core import dtypes

    return dtypes.convert_dtype("int64")

__all__ = [
    "mm", "floor_mod", "reverse", "frexp", "gammaln", "multigammaln",
    "i0e", "i1", "i1e", "polar", "signbit", "nanquantile",
    "cumulative_trapezoid", "combinations", "broadcast_shape",
    "create_tensor", "is_complex", "is_floating_point", "is_integer",
    "diag_embed", "diagonal_scatter", "dsplit", "hsplit", "vsplit",
    "split_with_num", "index_fill", "fill", "fill_diagonal", "multiplex",
    "select_scatter", "slice_scatter", "unstack", "as_strided",
    "top_p_sampling", "uniform_", "normal_", "exponential_", "cauchy_",
    "geometric_",
]


# ---------------------------- aliases ----------------------------

def mm(input, mat2, name=None):
    """Alias of matmul (paddle.mm)."""
    from .linalg import matmul

    return matmul(input, mat2)


def floor_mod(x, y, name=None):
    """Alias of mod (paddle.floor_mod)."""
    from .math import mod

    return mod(x, y)


def reverse(x, axis, name=None):
    """Alias of flip (paddle.reverse)."""
    from .manipulation import flip

    return flip(x, axis)


# ---------------------------- math ----------------------------

@op("frexp")
def frexp(x, name=None):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


@op("gammaln")
def gammaln(x, name=None):
    return jax.scipy.special.gammaln(x)


@op("multigammaln")
def multigammaln(x, p, name=None):
    const = 0.25 * p * (p - 1) * _math.log(_math.pi)
    terms = [jax.scipy.special.gammaln(x - 0.5 * i) for i in range(p)]
    return const + sum(terms[1:], terms[0])


@op("i0e")
def i0e(x, name=None):
    return jax.scipy.special.i0e(x)


@op("i1")
def i1(x, name=None):
    return jax.scipy.special.i1(x)


@op("i1e")
def i1e(x, name=None):
    return jax.scipy.special.i1e(x)


@op("polar")
def polar(abs, angle, name=None):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@op("signbit")
def signbit(x, name=None):
    return jnp.signbit(x)


@op("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim,
                           method=interpolation)


@op("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    n = y.shape[axis]
    lo = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    hi = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    if x is not None:
        xlo = jax.lax.slice_in_dim(x, 0, n - 1, axis=axis)
        xhi = jax.lax.slice_in_dim(x, 1, n, axis=axis)
        widths = xhi - xlo
    else:
        widths = dx if dx is not None else 1.0
    return jnp.cumsum((lo + hi) * 0.5 * widths, axis=axis)


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements (paddle.combinations).
    The index set is static (depends only on length), so this traces to one
    gather."""
    def f(v):
        n = v.shape[0]
        picker = (itertools.combinations_with_replacement if with_replacement
                  else itertools.combinations)
        idx = np.asarray(list(picker(range(n), r)), np.int32).reshape(-1, r)
        return v[idx]

    return apply("combinations", f, x)


def broadcast_shape(x_shape, y_shape):
    """Static shape algebra (paddle.broadcast_shape) — pure host-side."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def create_tensor(dtype="float32", name=None, persistable=False):
    from ..core import dtypes

    return Tensor(jnp.zeros((0,), dtypes.convert_dtype(dtype)))


def _dtype_of(x):
    return x._value.dtype if isinstance(x, Tensor) else jnp.asarray(x).dtype


def is_complex(x):
    return jnp.issubdtype(_dtype_of(x), jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_dtype_of(x), jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_dtype_of(x), jnp.integer)


# ---------------------------- manipulation ----------------------------

@op("diag_embed")
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    n = input.shape[-1]
    m = n + abs(offset)
    rows = jnp.arange(n) + max(0, -offset)
    cols = jnp.arange(n) + max(0, offset)
    out = jnp.zeros(input.shape[:-1] + (m, m), input.dtype)
    out = out.at[..., rows, cols].set(input)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


@op("diagonal_scatter")
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    nd = x.ndim
    a1, a2 = axis1 % nd, axis2 % nd
    moved = jnp.moveaxis(x, (a1, a2), (nd - 2, nd - 1))
    h, w = moved.shape[-2], moved.shape[-1]
    k = min(h, w - offset) if offset >= 0 else min(h + offset, w)
    rows = jnp.arange(k) + max(0, -offset)
    cols = jnp.arange(k) + max(0, offset)
    moved = moved.at[..., rows, cols].set(y)
    return jnp.moveaxis(moved, (nd - 2, nd - 1), (a1, a2))


def _nsplit(x, num_or_sections, axis, min_ndim, api):
    def f(v):
        if v.ndim < min_ndim:
            raise ValueError(f"{api} expects at least {min_ndim}-D input, "
                             f"got {v.ndim}-D")
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        sections = np.cumsum(num_or_sections[:-1]).tolist()
        return tuple(jnp.split(v, sections, axis=axis))

    return list(apply(api, f, x))


def vsplit(x, num_or_sections, name=None):
    return _nsplit(x, num_or_sections, 0, 2, "vsplit")


def hsplit(x, num_or_sections, name=None):
    return _nsplit(x, num_or_sections, 1, 2, "hsplit")


def dsplit(x, num_or_sections, name=None):
    return _nsplit(x, num_or_sections, 2, 3, "dsplit")


def split_with_num(x, num, axis=0, name=None):
    return _nsplit(x, num, axis, 1, "split_with_num")


@op("index_fill")
def index_fill(x, index, axis, value, name=None):
    idx = [slice(None)] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(value)


@op("fill")
def fill(x, value, name=None):
    return jnp.full_like(x, value)


@op("fill_diagonal")
def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    h, w = x.shape[-2], x.shape[-1]
    k = min(h, w - offset) if offset >= 0 else min(h + offset, w)
    rows = jnp.arange(k) + max(0, -offset)
    cols = jnp.arange(k) + max(0, offset)
    return x.at[..., rows, cols].set(value)


def multiplex(inputs, index, name=None):
    """out[i] = inputs[index[i]][i] (paddle.multiplex)."""
    def f(idx, *vs):
        stacked = jnp.stack(vs)
        sel = idx.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]

    return apply("multiplex", f, index, *inputs)


@op("select_scatter")
def select_scatter(x, values, axis, index, name=None):
    idx = [slice(None)] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(values)


@op("slice_scatter")
def slice_scatter(x, value, axes=None, starts=None, ends=None, strides=None,
                  name=None):
    axes = axes or []
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts or [], ends or [],
                           strides or [1] * len(axes)):
        idx[a % x.ndim] = slice(s, e, st)
    return x.at[tuple(idx)].set(value)


def unstack(x, axis=0, num=None, name=None):
    def f(v):
        n = num or v.shape[axis]
        parts = jnp.split(v, n, axis=axis)
        return tuple(jnp.squeeze(p, axis=axis) for p in parts)

    return list(apply("unstack", f, x))


@op("as_strided")
def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view as a gather over the flattened buffer (paddle.as_strided;
    TPU has no aliasing views — XLA fuses the gather)."""
    flat = x.reshape(-1)
    idx = jnp.asarray(offset, jnp.int32)
    for dim, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(dim, dtype=jnp.int32) * st
    return flat[idx.reshape(shape)]


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus sampling over the last axis (paddle.top_p_sampling):
    keep the smallest prefix of descending-prob tokens whose mass ≥ p,
    renormalize, sample one id per row. Returns (probs, ids)."""
    from ..core import rng

    key = rng.default_generator.split()

    def f(probs, p):
        order = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, order, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p < p.reshape(-1, 1)
        keep = keep.at[..., 0].set(True)  # always keep the top token
        filtered = jnp.where(keep, sorted_p, 0.0)
        filtered = filtered / jnp.sum(filtered, axis=-1, keepdims=True)
        choice = jax.random.categorical(key, jnp.log(filtered + 1e-30),
                                        axis=-1)
        ids = jnp.take_along_axis(order, choice[..., None], axis=-1)
        val = jnp.take_along_axis(probs, ids, axis=-1)
        return val, ids.astype(_index_dtype())

    return apply("top_p_sampling", f, x, ps)


# ------------------------ in-place random fills ------------------------

def _rand01(shape, dtype):
    from ..core import rng

    key = rng.default_generator.split()
    return jax.random.uniform(key, shape, dtype)


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    u = _rand01(tuple(x.shape), x._value.dtype)
    return x._rebind(Tensor(min + (max - min) * u))


def normal_(x, mean=0.0, std=1.0, name=None):
    from ..core import rng

    key = rng.default_generator.split()
    v = mean + std * jax.random.normal(key, tuple(x.shape), x._value.dtype)
    return x._rebind(Tensor(v))


def exponential_(x, lam=1.0, name=None):
    u = _rand01(tuple(x.shape), x._value.dtype)
    return x._rebind(Tensor(-jnp.log1p(-u) / lam))


def cauchy_(x, loc=0, scale=1, name=None):
    u = _rand01(tuple(x.shape), x._value.dtype)
    return x._rebind(Tensor(loc + scale * jnp.tan(jnp.pi * (u - 0.5))))


def geometric_(x, probs, name=None):
    u = _rand01(tuple(x.shape), x._value.dtype)
    return x._rebind(Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-probs)) + 1))


# ------------------- manifest batch 2: math/indexing -------------------

@op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * (x @ y)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    from ..core import dtypes

    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    from ..core import dtypes

    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtypes.convert_dtype(dtype)))


@op("clip_by_norm")
def clip_by_norm(x, max_norm, name=None):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return x * jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))


@op("fill_diagonal_tensor")
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    nd = x.ndim
    a1, a2 = dim1 % nd, dim2 % nd
    moved = jnp.moveaxis(x, (a1, a2), (nd - 2, nd - 1))
    h, w = moved.shape[-2], moved.shape[-1]
    k = min(h, w - offset) if offset >= 0 else min(h + offset, w)
    rows = jnp.arange(k) + max(0, -offset)
    cols = jnp.arange(k) + max(0, offset)
    # y carries the diagonal values (diag axis last, reference layout)
    moved = moved.at[..., rows, cols].set(y)
    return jnp.moveaxis(moved, (nd - 2, nd - 1), (a1, a2))


@op("identity_loss")
def identity_loss(x, reduction="none", name=None):
    red = {"none": lambda v: v, 0: lambda v: v,
           "sum": jnp.sum, 1: jnp.sum,
           "mean": jnp.mean, 2: jnp.mean}
    return red[reduction](x)


@op("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    n = nt // seg_num
    v = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    pad = jnp.pad(v, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
    back = pad[:, :seg_num, :c1]          # shift left (from t-1 view)
    fwd = pad[:, 2:, c1:c2]               # shift right
    keep = v[:, :, c2:]
    out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


# ------------------- random sampling creation ops -------------------

def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    from ..core import dtypes, rng

    key = rng.default_generator.split()
    dt = dtypes.convert_dtype(dtype)
    return Tensor(mean + std * jax.random.normal(key, tuple(shape), dt))


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, 1) elementwise (paddle.standard_gamma)."""
    from ..core import rng

    key = rng.default_generator.split()

    def f(a):
        return jax.random.gamma(key, a)

    return apply("standard_gamma", f, x)


def binomial(count, prob, name=None):
    from ..core import rng

    key = rng.default_generator.split()

    def f(n, p):
        return jax.random.binomial(key, n.astype(jnp.float32),
                                   p).astype(_index_dtype())

    return apply("binomial", f, count, prob)


def dirichlet(alpha, name=None):
    from ..core import rng

    key = rng.default_generator.split()

    def f(a):
        return jax.random.dirichlet(key, a)

    return apply("dirichlet", f, alpha)


# ------------------- host-side sequence/metric ops -------------------

def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per batch row (paddle.edit_distance; host-side
    DP like the reference CPU kernel)."""
    a = np.asarray(input._value if isinstance(input, Tensor) else input)
    b = np.asarray(label._value if isinstance(label, Tensor) else label)
    la = (np.asarray(input_length._value if isinstance(input_length, Tensor)
                     else input_length) if input_length is not None
          else np.full(a.shape[0], a.shape[1]))
    lb = (np.asarray(label_length._value if isinstance(label_length, Tensor)
                     else label_length) if label_length is not None
          else np.full(b.shape[0], b.shape[1]))
    ignored = set(ignored_tokens or ())
    dists = np.zeros((a.shape[0], 1), np.float32)
    counts = np.zeros((a.shape[0], 1), np.int64)
    for i in range(a.shape[0]):
        s1 = [t for t in a[i, :int(la[i])] if t not in ignored]
        s2 = [t for t in b[i, :int(lb[i])] if t not in ignored]
        m, n = len(s1), len(s2)
        dp = np.arange(n + 1, dtype=np.float32)
        for r in range(1, m + 1):
            prev = dp.copy()
            dp[0] = r
            for c in range(1, n + 1):
                dp[c] = min(prev[c] + 1, dp[c - 1] + 1,
                            prev[c - 1] + (s1[r - 1] != s2[c - 1]))
        d = dp[n]
        dists[i, 0] = d / max(1, n) if normalized else d
        counts[i, 0] = max(1, n)
    return Tensor(dists), Tensor(counts)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (paddle.text.viterbi_decode role) via lax.scan —
    compiled DP, TPU-friendly."""
    def f(emis, trans, lens):
        b, t, n = emis.shape
        if include_bos_eos_tag:
            # tag n-2 = BOS, n-1 = EOS (reference convention)
            start = trans[n - 2][None, :]
            alpha0 = emis[:, 0] + start
        else:
            alpha0 = emis[:, 0]

        def step(carry, xt):
            alpha, idx = carry
            scores = alpha[:, :, None] + trans[None, :, :]
            best = jnp.max(scores, axis=1) + xt
            bp = jnp.argmax(scores, axis=1)
            return (best, idx + 1), bp

        (alpha, _), bps = jax.lax.scan(
            step, (alpha0, jnp.zeros((), jnp.int32)),
            jnp.swapaxes(emis[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, n - 1][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)

        def back(carry, bp):
            # carry = tag at time i+1; emit it at slot i, carry tag at i
            cur = carry
            prev = jnp.take_along_axis(bp, cur[:, None], axis=1)[:, 0]
            return prev, cur

        first, ys = jax.lax.scan(back, last, bps, reverse=True)
        path = (jnp.concatenate([first[:, None], jnp.swapaxes(ys, 0, 1)],
                                axis=1) if t > 1 else last[:, None])
        return scores, path.astype(_index_dtype())

    return apply("viterbi_decode", f, potentials, transition_params, lengths)


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (paddle.nn.functional.gather_tree): walk
    parent pointers from the last step back to the root."""
    iv = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
    pv = np.asarray(parents._value if isinstance(parents, Tensor)
                    else parents)
    t, b, w = iv.shape
    out = np.zeros_like(iv)
    for bi in range(b):
        for wi in range(w):
            beam = wi
            for ti in range(t - 1, -1, -1):
                out[ti, bi, wi] = iv[ti, bi, beam]
                beam = int(pv[ti, bi, beam])
    return Tensor(out)


def auc(x, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Batch AUC (paddle.static.auc role, eager form)."""
    pred = np.asarray(x._value if isinstance(x, Tensor) else x)
    lab = np.asarray(label._value if isinstance(label, Tensor)
                     else label).reshape(-1)
    score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
        else pred.reshape(-1)
    order = np.argsort(-score)
    lab = lab[order]
    tps = np.cumsum(lab)
    fps = np.cumsum(1 - lab)
    tot_p = max(1, int(tps[-1]))
    tot_f = max(1, int(fps[-1]))
    tpr = np.concatenate([[0.0], tps / tot_p])
    fpr = np.concatenate([[0.0], fps / tot_f])
    return Tensor(np.asarray(np.trapezoid(tpr, fpr), np.float32))


__all__ += [
    "addmm", "tril_indices", "triu_indices", "clip_by_norm",
    "fill_diagonal_tensor", "identity_loss", "temporal_shift", "gaussian",
    "standard_gamma", "binomial", "dirichlet", "edit_distance",
    "viterbi_decode", "gather_tree", "auc",
]
