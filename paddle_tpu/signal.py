"""Signal processing: frame / overlap_add / stft / istft
(paddle.signal parity: `/root/reference/python/paddle/signal.py`).

TPU-first: framing is a gather with a static index grid (XLA-fusable, no
dynamic shapes); stft = frame -> window -> batched FFT on the last axis.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_raw(x, frame_length, hop_length, axis=-1):
    axis = axis % x.ndim
    n = x.shape[axis]
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    frames = jnp.take(x, idx.reshape(-1), axis=axis)
    new_shape = (x.shape[:axis] + (num_frames, frame_length)
                 + x.shape[axis + 1:])
    frames = frames.reshape(new_shape)
    if axis == x.ndim - 1:
        # reference layout: [..., frame_length, num_frames]
        frames = jnp.swapaxes(frames, -1, -2)
    return frames


@op("frame")
def frame(x, frame_length, hop_length, axis=-1, name=None):
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    return _frame_raw(x, frame_length, hop_length, axis=axis)


def _overlap_add_raw(x, hop_length, axis=-1):
    # reference layouts: axis=-1 -> [..., frame_length, num_frames] (result
    # seq on last axis); axis=0 -> [num_frames, frame_length, ...] (seq first)
    axis = axis % x.ndim
    if axis == x.ndim - 1:
        x = jnp.swapaxes(x, -1, -2)  # -> [..., num_frames, frame_length]
        seq_first = False
    else:
        x = jnp.moveaxis(x, (0, 1), (-2, -1))  # -> [..., nf, fl]
        seq_first = True
    num_frames, frame_length = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # [nf, fl]
    batch_shape = x.shape[:-2]
    flat = x.reshape((-1, num_frames * frame_length))
    out = jnp.zeros((flat.shape[0], out_len), dtype=x.dtype)
    out = out.at[:, idx.reshape(-1)].add(flat)
    out = out.reshape(batch_shape + (out_len,))
    if seq_first:
        out = jnp.moveaxis(out, -1, 0)
    return out


@op("overlap_add")
def overlap_add(x, hop_length, axis=-1, name=None):
    return _overlap_add_raw(x, hop_length, axis=axis)


@op("stft")
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None, :]
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)], mode=pad_mode)
    frames = _frame_raw(x, n_fft, hop_length, axis=-1)  # [..., n_fft, nf]
    frames = jnp.swapaxes(frames, -1, -2) * window  # [..., nf, n_fft]
    if onesided and not jnp.iscomplexobj(x):
        spec = jnp.fft.rfft(frames, axis=-1)
    else:
        spec = jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    spec = jnp.swapaxes(spec, -1, -2)  # [..., freq, num_frames]
    return spec[0] if squeeze else spec


@op("istft")
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    spec = jnp.swapaxes(x, -1, -2)  # [..., num_frames, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, axis=-1)
        if not return_complex:
            frames = frames.real
    frames = frames * window
    sig = _overlap_add_raw(jnp.swapaxes(frames, -1, -2), hop_length, axis=-1)
    # normalise by summed squared window (NOLA)
    wsq = jnp.tile(window ** 2, (frames.shape[-2], 1))
    norm = _overlap_add_raw(jnp.swapaxes(wsq, -1, -2), hop_length, axis=-1)
    sig = sig / jnp.maximum(norm, 1e-11)
    if center:
        pad = n_fft // 2
        sig = sig[..., pad:sig.shape[-1] - pad]
    if length is not None:
        sig = sig[..., :length]
    return sig[0] if squeeze else sig
