"""paddle_tpu.observability — framework-wide runtime telemetry.

Three pillars (docs/OBSERVABILITY.md):
  * `metrics`    — process-local counters / gauges / histograms with
    labels; disabled by default, near-zero cost when disabled; JSONL
    snapshot + Prometheus text export.  Wired into flash-attention
    dispatch (tier + gate-reject counters), the autotune cache,
    `jit.to_static` trace cache / retraces, collectives, and the
    allocator peak.
  * `step_stats` — `StepTimer` for train/serve loops and bench.py:
    per-step wall, tokens/s, MFU, compile-time ledger, transfer bytes,
    streamed as chip-session-compatible JSONL.
  * `flight`     — bounded ring of recent structured events (dispatch
    decisions, gate rejects, retraces) dumped on crash or on demand.
  * `trace`      — unified span timeline (Chrome trace-event / Perfetto
    export): RecordEvent scopes, flight events, StepTimer frames,
    collective/pipeline-stage spans, and compile spans annotated by
    `xla_cost` all land in ONE correlated buffer.
  * `xla_cost`   — compile-time `cost_analysis()`/`memory_analysis()`
    capture: FLOPs/bytes per compiled program as span metadata + gauges.

`attach()` turns the whole stack on with a stable snapshot schema —
what `bench.py --telemetry` calls.
"""
from __future__ import annotations

from . import (  # noqa: F401
    export, flight, goodput, lifecycle, metrics, request_trace, slo,
    step_stats, tenant_ledger, timeseries, trace, xla_cost,
)
from .step_stats import StepTimer  # noqa: F401

__all__ = ["metrics", "flight", "step_stats", "trace", "xla_cost",
           "request_trace", "slo", "export", "goodput", "tenant_ledger",
           "timeseries", "lifecycle", "StepTimer", "attach", "detach"]

# The snapshot-schema floor `attach()` guarantees: these counters exist
# (at 0) in every telemetry snapshot even when the path never fired in
# this process — a CPU bench run still reports autotune.hit == 0 rather
# than omitting the key (ISSUE 1 acceptance schema).  Every entry here
# carries EXACTLY the label set its live increment site uses, so the
# declared key is the key that counts (zeros never sit next to the real
# series under a different label set).
_SCHEMA_COUNTERS = tuple(
    [("flash.dispatch", {"tier": t})
     for t in ("transpose", "kv", "flat", "mh", "fallback", "biased")]
    + [("autotune.hit", {}), ("autotune.miss", {})]
    + [("autotune.cross_layout_reject", {"layout": lt})
       for lt in ("kv", "flat", "mh")]
    + [("jit.trace_cache.hit", {}), ("jit.trace_cache.miss", {}),
       ("jit.retrace", {})]
    + [("collective.calls", {"kind": k})
       for k in ("all_reduce", "all_gather", "reduce_scatter", "alltoall",
                 "alltoall_single", "broadcast", "send", "barrier")]
    # EQuARX quantized-collective tier (ISSUE 11, docs/SHARDING.md):
    # which additive syncs rode the wire quantized, by payload codec
    + [("collective.quantized", {"kind": k, "precision": p})
       for k in ("all_reduce", "reduce_scatter")
       for p in ("bf16", "int8")]
    + [("collective.quantized_tier", {"precision": p})
       for p in ("bf16", "int8")]
    # resilience subsystem (ISSUE 3): fault injections, retry traffic,
    # guard skips, checkpoint/guard rollbacks, watchdog trips — declared
    # so a clean run reports zeros instead of omitting the keys
    + [("resilience.faults", {"point": p})
       for p in ("checkpoint.write", "collective.call", "dataloader.batch",
                 "jit.compile", "train.step", "serving.request",
                 "store.op", "router.forward", "router.stream_read",
                 "router.resume_verify", "replica.crash")]
    + [("resilience.retries", {"policy": p})
       for p in ("collective", "elastic.heartbeat", "serving",
                 "dataloader", "jit.compile")]
    + [("resilience.giveups", {"policy": p})
       for p in ("collective", "elastic.heartbeat", "serving",
                 "dataloader", "jit.compile")]
    + [("resilience.circuit_open", {"policy": p})
       for p in ("collective", "elastic.heartbeat", "serving")]
    + [("resilience.skipped_steps", {"source": s})
       for s in ("guard", "amp", "amp_floor")]
    + [("resilience.rollbacks", {}), ("resilience.watchdog_trips", {}),
       ("resilience.degraded_batches", {})]
    # overload/preemption runtime (ISSUE 5): admission sheds by reason,
    # preemption signals by name, emergency checkpoints, serving drains
    + [("resilience.shed_requests", {"reason": r})
       for r in ("queue_full", "queue_timeout", "deadline", "draining",
                 "no_replicas", "deadline_exceeded")]
    # multi-tenant QoS (ISSUE 18): per-class shed and preemption
    # counters — the class set mirrors inference.qos.CLASSES (hardcoded
    # here: observability stays standalone, same discipline as
    # request_trace's header validation set)
    + [("qos.shed", {"class": c}) for c in ("paid", "free", "batch")]
    + [("qos.preemptions", {"class": c})
       for c in ("paid", "free", "batch")]
    + [("preemption.signals", {"signal": s})
       for s in ("SIGTERM", "SIGINT")]
    + [("preemption.maintenance_events", {}),
       ("preemption.checkpoints", {}), ("preemption.drains", {}),
       ("preemption.callback_errors", {})]
    # request-level serving telemetry (ISSUE 7): per-status request
    # counters on both sides of the hop — a fresh server reports zeros
    # for every status class instead of omitting the keys
    + [("serving.requests", {"status": s})
       for s in ("ok", "client_error", "shed", "timeout", "error")]
    + [("client.requests", {"status": s})
       for s in ("ok", "shed_retry", "error")]
    # continuous-batching engine (ISSUE 8): sequence lifecycle events,
    # accepted tokens, and the paged-attention dispatch tier — a fresh
    # engine reports zeros instead of omitting the keys
    + [("engine.sequences", {"event": e})
       for e in ("submitted", "admitted", "completed", "cancelled",
                 "evicted")]
    + [("engine.tokens", {})]
    + [("paged.dispatch", {"tier": t}) for t in ("pallas", "fallback")]
    # speculative decoding (ISSUE 12): per-pass draft-token outcomes —
    # accepted counts committed draft proposals, rejected the discarded
    # tail (the acceptance rate is accepted/(accepted+rejected))
    + [("engine.spec_decode", {"result": r})
       for r in ("accepted", "rejected")]
    # fleet router (ISSUE 9): failure-triggered failovers, replica
    # ejections/re-admissions, and per-endpoint routed-request outcomes
    # — a fresh router reports zeros instead of omitting the keys
    + [("router.failovers", {}), ("router.ejections", {}),
       ("router.readmissions", {})]
    + [("router.requests", {"endpoint": ep, "status": s})
       for ep in ("predict", "generate")
       for s in ("ok", "client_error", "shed", "interrupted", "error")]
    # mid-stream failover (ISSUE 20): router-side resume outcomes and
    # the replica-side resume-prefill cache attribution — a healthy
    # fleet shows zeros, never absent keys
    + [("router.stream_resumes", {"outcome": o})
       for o in ("ok", "diverged", "exhausted")]
    + [("serving.resume_prefill", {"cache": c})
       for c in ("hit", "partial", "miss")]
    # prefix caching (ISSUE 13): admission-time cache outcomes and LRU
    # reclaims on the engine side, affinity pick outcomes on the router
    # side (counted only for fingerprinted /generate requests)
    + [("engine.prefix_cache", {"event": e})
       for e in ("hit", "miss", "evict")]
    + [("router.affinity", {"outcome": o})
       for o in ("affine", "least_loaded")]
    # autoscaler (ISSUE 14): one decision per control tick — a healthy
    # steady-state fleet shows a growing `hold` count next to zero
    # up/down, which is itself the signal the loop is alive.
    # `up_predictive` (ISSUE 15) is a scale-up fired by the timeseries
    # plane's queue-growth derivative BEFORE burn/occupancy thresholds
    # crossed — the leading-vs-lagging split is first-class telemetry
    + [("autoscaler.decisions", {"action": a})
       for a in ("up", "down", "hold", "up_predictive")]
    # anomaly watchdog (ISSUE 15): rolling-baseline latency-regression
    # detections by kind — zero on a healthy server, never absent
    + [("telemetry.anomalies", {"kind": k})
       for k in ("ttft", "itl")]
    # tenant metering (ISSUE 16): bounded-cardinality aggregate mirror
    # of the ledger — the per-tenant top-K table itself lives ONLY in
    # /debug/tenants and telemetry dumps, never the metrics registry
    + [("tenant.requests", {"status": s})
       for s in ("ok", "shed", "client_error", "error")]
    # replica lifecycle (ISSUE 17): spawn count + strict-stamp
    # violations — bounded, per-process (supervisor and replica each
    # count their own view of a spawn)
    + [("lifecycle.spawns", {}), ("lifecycle.double_stamps", {})]
)

# Gauges attach() zeroes so the admission-control state is always
# present in a snapshot (a server that never saw traffic still reports
# inflight=0 rather than omitting the key).  Entries are either a bare
# name or a (name, labels) pair for labeled gauge series.
_SCHEMA_GAUGES = ("serving.inflight", "serving.queue_depth",
                  "serving.admission_limit",
                  # engine state (ISSUE 8): live batch + page pool
                  "engine.active_sequences", "engine.waiting_sequences",
                  "engine.batch_occupancy", "engine.page_utilization",
                  # quantized decode (ISSUE 12): draft proposal length
                  "engine.spec_tokens",
                  # prefix cache (ISSUE 13): radix-index size + lifetime
                  # hit rate — the /ready payload's gauge pair
                  "engine.prefix_cached_tokens",
                  "engine.prefix_cache_hit_rate",
                  # tenant ledger (ISSUE 16): sketch occupancy + overflow
                  # mass — the only per-registry trace of the top-K table
                  "tenant.tracked", "tenant.other_tokens") \
    + tuple(("telemetry.timeseries_samples", {"sampler": s})
            # timeseries sampler health (ISSUE 15): total samples per
            # sampler — a flat-lined value is that sampler's own
            # outage alarm (labeled: a router and a server in one
            # process must not hide behind each other's count)
            for s in ("serving", "router")) \
    + tuple(("router.replicas", {"state": s})
            for s in ("up", "draining", "ejected", "down")) \
    + tuple(("router.capacity", {"endpoint": ep})
            for ep in ("predict", "generate")) \
    + tuple(("autoscaler.replicas", {"state": s})
            for s in ("target", "actual")) \
    + tuple(("engine.weight_precision", {"precision": p})
            for p in ("full", "bf16", "int8")) \
    + tuple(("paged.pool_precision", {"precision": p})
            for p in ("full", "int8")) \
    + tuple(("lifecycle.phase_ms", {"phase": p})
            # replica lifecycle (ISSUE 17): ms of the just-closed phase;
            # proc_spawn is the anchor so it never closes a phase.  The
            # per-program lifecycle.compile_ms series is bounded by the
            # ledger's label cap; only the ~total sum is pre-declared
            for p in lifecycle.PHASES[1:]) \
    + (("lifecycle.compile_ms", {"program": "~total"}),
       # autoscaler's observed spawn->routable estimate (ISSUE 17):
       # 0 until the first spawn completes, then the fleet median
       "autoscaler.observed_spawn_ms") \
    + tuple(("slo.burn_rate", {"endpoint": ep, "class": c})
            # per-class SLO burn (ISSUE 18): zero before traffic, so a
            # dashboard watching the paid tier has its key from boot
            for ep in ("predict", "generate")
            for c in ("paid", "free", "batch"))


# Histograms attach() pre-registers EMPTY (full bucket ladder, count 0)
# so a fresh server's /metrics and snapshot expose the series before
# the first observation — the ITL acceptance surface (ISSUE 15).
_SCHEMA_HISTS = (
    ("serving.itl_ms", {"endpoint": "generate"}),
    # mid-stream failover (ISSUE 20): the client-visible gap between
    # the last token the dead replica delivered and the first token
    # the resume replica delivered — THE latency cost of a resume
    ("router.resume_gap_ms", {}),
)


def attach(crash_hook: bool = True):
    """Enable the full telemetry stack: metrics registry on, schema
    counters pre-declared, flight recorder on (+ crash-dump excepthook),
    span tracer buffering.  Returns the metrics registry (snapshot() it
    at the end of the run; `trace.export(path)` writes the timeline)."""
    metrics.enable()
    for name, labels in _SCHEMA_COUNTERS:
        metrics.declare(name, **labels)
    for entry in _SCHEMA_GAUGES:
        if isinstance(entry, tuple):
            metrics.set_gauge(entry[0], 0, **entry[1])
        else:
            metrics.set_gauge(entry, 0)
    for name, labels in _SCHEMA_HISTS:
        metrics.declare_hist(name, **labels)
    flight.get_recorder().enabled = True
    trace.enable()
    if crash_hook:
        flight.install_crash_hook()
    return metrics.get_registry()


def detach():
    """Disable metric recording and span buffering (flight stays on — it
    is cheap and the crash evidence is the point).  Does not clear
    collected data."""
    metrics.disable()
    trace.disable()
