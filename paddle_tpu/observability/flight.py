"""Flight recorder: bounded in-memory ring of recent structured events.

The black box for incidents like round-5's "tunnel window closed
mid-compile": kernel dispatch decisions, gate rejects, retraces, and
collective anomalies append tiny dicts to a ring; on crash (installed
excepthook) or on demand (`dump()`) the ring lands on disk as JSONL, so
the *last thing the process decided* survives the process.

Always-on by default: events fire at dispatch/trace frequency (not per
device step), so the cost is a dict construction and a deque append.
Set ``recorder.enabled = False`` (or env ``PADDLE_TPU_FLIGHT=0``) to
silence it entirely.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from . import metrics as _metrics
from . import trace as _trace

__all__ = ["FlightRecorder", "get_recorder", "record", "events", "dump",
           "clear", "install_crash_hook"]

DEFAULT_CAPACITY = 512


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        import collections

        self._events = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self.enabled = os.environ.get("PADDLE_TPU_FLIGHT", "1") not in (
            "0", "false", "False")

    def record(self, kind: str, **data) -> None:
        """Append one event. `kind` is a dotted event name
        (``flash.gate_reject``, ``jit.retrace``, ...); payload values
        should be JSON-friendly (shapes as lists, not arrays)."""
        if not self.enabled:
            return
        evt = {"t": time.time(), "kind": str(kind)}
        scope = _metrics.current_scope()
        if scope is not None:
            evt["scope"] = scope
        evt.update(data)
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            self._events.append(evt)
        # correlate onto the span timeline: every ring event doubles as
        # an instant between the spans that caused it (only when the
        # tracer is buffering — instant() is one branch otherwise).  A
        # payload key colliding with instant()'s own parameters must not
        # sink the recording path.
        try:
            _trace.instant(kind, cat="flight", **data)
        except TypeError:
            _trace.instant(kind, cat="flight")

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: str | None = None, reason: str = "on_demand") -> str:
        """Write the ring to `path` as JSONL (one event per line, headed
        by a dump marker carrying the reason).  Default path:
        ``$PADDLE_TPU_FLIGHT_PATH`` or ``flight_<pid>.jsonl`` in cwd."""
        path = path or os.environ.get(
            "PADDLE_TPU_FLIGHT_PATH", f"flight_{os.getpid()}.jsonl")
        evts = self.events()
        header = {"t": time.time(), "kind": "flight.dump", "reason": reason,
                  "n_events": len(evts), "pid": os.getpid()}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for e in evts:
                f.write(json.dumps(e, default=str) + "\n")
        return path


_default = FlightRecorder()
_hook_installed = False


def get_recorder() -> FlightRecorder:
    return _default


def record(kind, **data):
    _default.record(kind, **data)


def events():
    return _default.events()


def dump(path=None, reason="on_demand"):
    return _default.dump(path, reason=reason)


def clear():
    _default.clear()


def install_crash_hook() -> None:
    """Chain onto sys.excepthook: an uncaught exception dumps the ring
    before the normal traceback prints.  Idempotent; the dump itself is
    guarded so a broken disk can never mask the original exception."""
    global _hook_installed
    if _hook_installed:
        return
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            if _default.events():
                p = _default.dump(reason=f"crash:{exc_type.__name__}")
                print(f"[observability] flight recorder dumped to {p}",
                      file=sys.stderr)
        except Exception as e:
            # a broken disk must never mask the original exception —
            # but the operator should know the black box is gone
            print(f"[observability] flight dump failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        prev(exc_type, exc, tb)

    sys.excepthook = hook
    _hook_installed = True
