"""Unified trace timeline: thread-safe span tracer with Perfetto export.

The fourth observability pillar (docs/OBSERVABILITY.md): PR 1 gave the
framework counters, a flight ring, and step-stats JSONL, but the signals
were siloed — a RecordEvent scope, a gate-reject flight event, and a
step wall could not be laid on ONE timeline.  This module is that
timeline:

  * spans   — monotonic-clock begin/end pairs with parent/child nesting
    per thread, labels, and a bounded event buffer (`span()` context
    manager, `traced()` decorator, or explicit `begin()`/`end()` for
    scope objects like profiler.RecordEvent);
  * instants — point events (the flight recorder mirrors every ring
    event here when the tracer is on, so dispatch decisions and gate
    rejects land between the spans that caused them);
  * frames  — step markers on a per-run synthetic track (StepTimer
    emits one per step record: the train loop's heartbeat row);
  * counters — numeric series ("C" events: allocator peak over time).

Export is Chrome trace-event JSON (the format Perfetto and
chrome://tracing open natively): complete events with real `pid`/`tid`,
`process_name`/`thread_name`/`thread_sort_index` metadata so nested
scopes render as stacked slices per thread instead of collapsing onto
one row, and synthetic tracks for frames/counters sorted below the real
threads.

Cost model: DISABLED by default — one attribute read + branch per call
(`observability.attach()`, `trace.enable()`, or env
``PADDLE_TPU_TRACE=1`` turn it on).  When enabled, a span is two clock
reads, a dict, and a deque append under a short lock; the buffer is
bounded (oldest events drop, the drop count is reported in the export).

This module keeps its top level stdlib-only AND free of package-relative
imports: `tools/analyze_chip_log.py` and `tools/perf_gate.py` file-load
it (like step_stats.py), so traces can be validated and merged without
importing jax-heavy `paddle_tpu`.
"""
from __future__ import annotations

import collections
import contextlib
import functools
import json
import os
import threading
import time

__all__ = [
    "SpanTracer", "get_tracer", "span", "traced", "begin", "end",
    "instant", "frame", "counter", "enable", "disable", "enabled",
    "clear", "events", "to_chrome", "export", "dump_jsonl",
    "current_span", "TRACE_PHASE", "SCHEMA_VERSION", "DEFAULT_CAPACITY",
    "validate_trace_stream", "summarize_trace_stream",
]

TRACE_PHASE = "trace_event"
SCHEMA_VERSION = "trace/v1"
DEFAULT_CAPACITY = 65536

# synthetic tracks (frames/counters) sort below real threads in the UI
_VIRTUAL_SORT_BASE = 1000


def _metrics_module():
    """The sibling metrics module, or None when file-loaded standalone."""
    try:
        from . import metrics  # type: ignore

        return metrics
    except ImportError:
        return None


class Span:
    """Open-span handle: mutate ``args`` before the span closes to attach
    metadata computed inside the span (e.g. xla_cost attaches the
    compiler's FLOPs estimate to the compile span that produced it)."""

    __slots__ = ("name", "cat", "args", "t0_us", "tid", "depth")

    def __init__(self, name, cat, args, t0_us, tid, depth):
        self.name = name
        self.cat = cat
        self.args = args
        self.t0_us = t0_us
        self.tid = tid
        self.depth = depth


class SpanTracer:
    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled=None):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=int(capacity))
        self.capacity = int(capacity)
        self._n_added = 0
        if enabled is None:
            enabled = os.environ.get("PADDLE_TPU_TRACE", "0") in (
                "1", "true", "True")
        self._enabled = bool(enabled)
        # one epoch per tracer: every ts is microseconds since this
        # monotonic origin, so spans/instants/frames from all threads
        # share a comparable clock
        self._epoch_ns = time.perf_counter_ns()
        self.wall_epoch = time.time()
        self.pid = os.getpid()
        self._tids: dict = {}        # threading ident -> small stable tid
        self._tid_names: dict = {}   # tid -> display name
        self._virtual: dict = {}     # track name -> tid
        self._local = threading.local()

    # ------------------------------ state ------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def enabled(self) -> bool:
        return self._enabled

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._n_added = 0

    def dropped(self) -> int:
        with self._lock:
            return max(0, self._n_added - self.capacity)

    def added(self) -> int:
        """Lifetime event count (monotone): the incremental-export
        cursor — `export.TelemetryExporter` dumps only events appended
        since its last dump by diffing this against its own cursor."""
        with self._lock:
            return self._n_added

    # ------------------------------ clock/ids ------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._epoch_ns) / 1e3

    def _tid(self) -> int:
        ident = threading.get_ident()
        # double-checked locking: the lock-free read is a GIL-atomic
        # dict get on this thread's own (immutable-once-written) entry
        tid = self._tids.get(ident)  # pt-lint: ok[PT102]
        if tid is None:
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
                    self._tid_names[tid] = threading.current_thread().name
        return tid

    def virtual_tid(self, track: str) -> int:
        """Stable tid for a synthetic track (frames, counters); rendered
        below the real threads via thread_sort_index."""
        # same double-checked pattern as _tid (lock-free first probe)
        tid = self._virtual.get(track)  # pt-lint: ok[PT102]
        if tid is None:
            with self._lock:
                tid = self._virtual.get(track)
                if tid is None:
                    tid = _VIRTUAL_SORT_BASE + len(self._virtual) + 1
                    self._virtual[track] = tid
                    self._tid_names[tid] = track
        return tid

    def _append(self, evt: dict) -> None:
        with self._lock:
            self._n_added += 1
            self._events.append(evt)

    # ------------------------------ spans ------------------------------
    def begin(self, name: str, cat: str = "host", **args):
        """Open a span on this thread; returns a Span token for end()
        (None when disabled — end(None) is a no-op, so begin/end pairs
        cost one branch each when tracing is off)."""
        if not self._enabled:
            return None
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        sp = Span(str(name), cat, dict(args), self._now_us(), self._tid(),
                  len(stack))
        stack.append(sp)
        return sp

    def end(self, sp) -> None:
        if sp is None:
            return
        t1 = self._now_us()
        stack = getattr(self._local, "stack", None)
        if stack and sp in stack:
            # tolerate unbalanced exits: drop this span and anything
            # opened (and never closed) inside it
            del stack[stack.index(sp):]
            if stack:
                sp.args.setdefault("parent", stack[-1].name)
        if not self._enabled:
            # disabled mid-span: the stack is already popped (a leaked
            # entry would mislabel every later span's parent), only the
            # event emission is skipped
            return
        metrics = _metrics_module()
        if metrics is not None:
            scope = metrics.current_scope()
            if scope is not None and scope != sp.name:
                sp.args.setdefault("scope", scope)
        self._append({"name": sp.name, "cat": sp.cat, "ph": "X",
                      "ts": round(sp.t0_us, 3),
                      "dur": round(max(t1 - sp.t0_us, 0.0), 3),
                      "pid": self.pid, "tid": sp.tid, "args": sp.args})

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        sp = self.begin(name, cat, **args)
        try:
            yield sp
        finally:
            self.end(sp)

    def traced(self, name=None, cat: str = "host"):
        """Decorator form: @trace.traced() or @trace.traced("label")."""
        def deco(fn):
            label = name or getattr(fn, "__qualname__",
                                    getattr(fn, "__name__", "fn"))

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                if not self._enabled:
                    return fn(*a, **kw)
                with self.span(label, cat=cat):
                    return fn(*a, **kw)

            return wrapper

        if callable(name):  # bare @traced usage
            fn, name = name, None
            return deco(fn)
        return deco

    def current_span(self):
        """Innermost open span name on this thread, or None."""
        stack = getattr(self._local, "stack", None)
        return stack[-1].name if stack else None

    # ------------------------- instants / frames -------------------------
    def instant(self, name: str, cat: str = "flight", **args) -> None:
        """Point event on the calling thread's track."""
        if not self._enabled:
            return
        self._append({"name": str(name), "cat": cat, "ph": "i", "s": "t",
                      "ts": round(self._now_us(), 3), "pid": self.pid,
                      "tid": self._tid(), "args": args})

    def frame(self, name: str, dur_us: float, track: str = "steps",
              ts_us=None, **args) -> None:
        """Step frame marker: a complete event on a synthetic per-run
        track.  ts defaults to `now - dur` (the caller reports a wall it
        just finished measuring)."""
        if not self._enabled:
            return
        dur_us = max(float(dur_us), 0.0)
        if ts_us is None:
            ts_us = self._now_us() - dur_us
        self._append({"name": str(name), "cat": "step", "ph": "X",
                      "ts": round(max(float(ts_us), 0.0), 3),
                      "dur": round(dur_us, 3), "pid": self.pid,
                      "tid": self.virtual_tid(track), "args": args})

    def counter(self, name: str, track: str = "counters", **series) -> None:
        """Numeric series sample ("C" event): series kwargs are the
        stacked values Perfetto plots."""
        if not self._enabled:
            return
        self._append({"name": str(name), "cat": "counter", "ph": "C",
                      "ts": round(self._now_us(), 3), "pid": self.pid,
                      "tid": self.virtual_tid(track), "args": series})

    # ------------------------------ export ------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def _metadata(self) -> list:
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "tid": 0, "args": {"name": "paddle_tpu"}}]
        with self._lock:
            names = dict(self._tid_names)
        for tid, name in sorted(names.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": self.pid,
                         "tid": tid, "args": {"name": name}})
            meta.append({"name": "thread_sort_index", "ph": "M",
                         "pid": self.pid, "tid": tid,
                         "args": {"sort_index": tid}})
        return meta

    def to_chrome(self) -> dict:
        """Chrome trace-event / Perfetto JSON object (json.dump-ready)."""
        return {
            "traceEvents": self._metadata() + self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"schema": SCHEMA_VERSION, "pid": self.pid,
                          "wall_epoch": self.wall_epoch,
                          "dropped_events": self.dropped()},
        }

    def export(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f, default=str)
        return path

    def dump_jsonl(self, path: str) -> str:
        """Append the buffer as chip-session-convention JSONL (one
        self-describing line per event, `phase`+`t` first) so trace
        events can interleave with step_stats / flight streams and
        `tools/analyze_chip_log.py` validates all three uniformly."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        t = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(path, "a") as f:
            for e in self.events():
                line = {"phase": TRACE_PHASE, "t": t}
                line.update(e)
                f.write(json.dumps(line, default=str) + "\n")
        return path


_default = SpanTracer()


def get_tracer() -> SpanTracer:
    return _default


# module-level conveniences bound to the default tracer — the form the
# instrumented call sites use (`trace.span("collective.all_reduce")`)
def span(name, cat="host", **args):
    return _default.span(name, cat=cat, **args)


def traced(name=None, cat="host"):
    return _default.traced(name, cat=cat)


def begin(name, cat="host", **args):
    return _default.begin(name, cat=cat, **args)


def end(sp):
    _default.end(sp)


def instant(name, cat="flight", **args):
    _default.instant(name, cat=cat, **args)


def frame(name, dur_us, track="steps", ts_us=None, **args):
    _default.frame(name, dur_us, track=track, ts_us=ts_us, **args)


def counter(name, track="counters", **series):
    _default.counter(name, track=track, **series)


def enable():
    _default.enable()


def disable():
    _default.disable()


def enabled():
    return _default.enabled()


def clear():
    _default.clear()


def events():
    return _default.events()


def to_chrome():
    return _default.to_chrome()


def export(path):
    return _default.export(path)


def dump_jsonl(path):
    return _default.dump_jsonl(path)


def current_span():
    return _default.current_span()


# ----------------------- stream validation -----------------------
#
# Pure functions over parsed JSONL entries, mirroring
# step_stats.validate_stream: tools/analyze_chip_log.py file-loads this
# module to get them — keep them stdlib-only.

_PHASES = {"X", "i", "C", "M", "B", "E"}


def validate_trace_stream(entries) -> list:
    """Schema errors for the trace_event entries in `entries` (non-trace
    entries are ignored — chip logs interleave phases).  Empty list =
    valid."""
    errors = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or e.get("phase") != TRACE_PHASE:
            continue
        ph = e.get("ph")
        if ph not in _PHASES:
            errors.append(f"entry {i}: bad ph {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e.get("name"):
            errors.append(f"entry {i}: missing/bad name")
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or isinstance(ts, bool) \
                    or ts < 0:
                errors.append(f"entry {i}: missing/negative ts")
        for key in ("pid", "tid"):
            if ph != "M" and not isinstance(e.get(key), int):
                errors.append(f"entry {i}: missing int {key}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool) \
                    or dur < 0:
                errors.append(f"entry {i}: X event missing/negative dur")
    return errors


def summarize_trace_stream(entries) -> dict:
    """Digest of a trace_event stream: event counts by ph, span count and
    total/max span wall per name (top ones), distinct tracks."""
    spans = {}
    by_ph: dict = {}
    tids = set()
    for e in entries:
        if not isinstance(e, dict) or e.get("phase") != TRACE_PHASE:
            continue
        ph = e.get("ph")
        by_ph[ph] = by_ph.get(ph, 0) + 1
        if "tid" in e:
            tids.add(e["tid"])
        if ph == "X" and isinstance(e.get("dur"), (int, float)):
            rec = spans.setdefault(e.get("name", "?"), [0, 0.0, 0.0])
            rec[0] += 1
            rec[1] += float(e["dur"])
            rec[2] = max(rec[2], float(e["dur"]))
    out = {"events": sum(by_ph.values()), "by_ph": by_ph,
           "tracks": len(tids)}
    if spans:
        top = sorted(spans.items(), key=lambda kv: -kv[1][1])[:10]
        out["spans"] = {
            name: {"count": c, "total_us": round(tot, 1),
                   "max_us": round(mx, 1)}
            for name, (c, tot, mx) in top}
    return out
