"""Time-series telemetry plane: rates, derivatives, and per-token
latency attribution (ISSUE 15).

Everything the registry (metrics.py) holds is a point-in-time value: a
counter says how many, never how fast; a gauge says where the queue is,
never where it is GOING.  This module adds the time dimension, bounded
by construction:

  * `TimeSeries` — a fixed-capacity ring of *frames* (one timestamped
    dict of name→value per sample) with the query math every consumer
    shares: `window()`, counter-aware reset-safe `rate()`, least-squares
    `derivative()` (the autoscaler's predictive signal), and a
    time-decayed `ewma()`.  O(capacity × names) memory, ever.
  * `TimeSeriesSampler` — a daemon that snapshots a DECLARED set of
    counters/gauges from the `MetricsRegistry` into a `TimeSeries` every
    `interval_s`.  Served on `GET /debug/timeseries` (serving + router),
    shipped incrementally in `TelemetryExporter` dumps (`frames_since`),
    merged fleet-wide by `tools/telemetry_agg.py` (per-process series,
    fleet-sum series, Perfetto counter tracks).
  * `RequestTimeline` — one request's latency story: admission → queue
    → prefill start/end → first token → per-decode-step token stamps
    (reservoir-bounded: past `PADDLE_TPU_ITL_TIMELINE_CAP` stamps the
    retained set decimates 2×, so memory stays O(cap) while coverage
    spans the whole stream) plus the top-K largest inter-token gaps
    with their timestamps — the stall evidence `GET /debug/requests/<id>`
    correlates against the scheduler's decision ring.
  * `DecisionRing` — the scheduler's bounded decision log: admit /
    evict-recompute / prefix-reclaim / defrag events with reason, seq
    ids, and page pressure at decision time.  `window(t0, t1)` answers
    "what did the scheduler do during THIS token gap".
  * `AnomalyDetector` — online rolling-baseline regression detection:
    the median of a recent window vs the median of the trailing
    baseline it displaces; a window median beyond `ratio`× the baseline
    fires a loud flight event + `telemetry.anomalies{kind}` counter
    (with a per-kind cooldown), so an ITL/TTFT cliff lands in telemetry
    before a human looks at a dashboard.  Steady noise stays silent: a
    persistent shift is absorbed into the baseline and stops firing.

Env knobs (read when the matching ctor arg is None):
  PADDLE_TPU_TIMESERIES_INTERVAL_S  sampler period (s)         (1.0)
  PADDLE_TPU_TIMESERIES_CAPACITY    frames kept per ring       (512)
  PADDLE_TPU_ITL_TIMELINE_CAP       token stamps per timeline  (256)
  PADDLE_TPU_ANOMALY_RATIO          window/baseline median bar (3.0)
  PADDLE_TPU_ANOMALY_WINDOW         recent-window length       (24)

stdlib-only on purpose (same contract as metrics.py): the engine's hot
path stamps timelines and the exporter ships frames without ever
paying a jax import.
"""
from __future__ import annotations

import collections
import os
import threading
import time

from . import metrics as _metrics

__all__ = [
    "TimeSeries", "TimeSeriesSampler", "RequestTimeline", "DecisionRing",
    "AnomalyDetector", "get_default_sampler", "set_default_sampler",
]

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 512
DEFAULT_TIMELINE_CAP = 256
DEFAULT_TOP_GAPS = 8


def _env_num(name, default, cast=float):
    # local on purpose (not resilience.overload._env_num): resilience
    # imports observability — this module importing it back would be a
    # package cycle
    raw = os.environ.get(name)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return cast(float(raw))
    except (TypeError, ValueError):
        return default


def _median(vals):
    return _metrics.quantile(sorted(vals), 0.5)


# ---------------------------------------------------------------------------
# the bounded series store + query math
# ---------------------------------------------------------------------------

class TimeSeries:
    """Fixed-capacity ring of frames.  A frame is one sampling instant:
    ``{"seq", "t" (monotonic), "wall", "values": {name: float}}``.
    Recording and every query take the ring lock — consumers are a
    ~1 Hz sampler and debug endpoints, not hot paths."""

    def __init__(self, capacity=None, clock=time.monotonic):
        if capacity is None:
            capacity = int(_env_num("PADDLE_TPU_TIMESERIES_CAPACITY",
                                    DEFAULT_CAPACITY, int))
        self.capacity = max(2, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._frames = collections.deque(maxlen=self.capacity)
        self._seq = 0

    # -- recording --
    def record(self, values, t=None, wall=None) -> int:
        """Append one frame; returns its seq.  `values` is copied."""
        vals = {str(k): float(v) for k, v in dict(values).items()}
        with self._lock:
            self._seq += 1
            self._frames.append({
                "seq": self._seq,
                "t": float(t) if t is not None else float(self.clock()),
                "wall": float(wall) if wall is not None else time.time(),
                "values": vals,
            })
            return self._seq

    # -- raw access --
    def frames(self) -> list:
        with self._lock:
            return list(self._frames)

    def frames_since(self, seq: int) -> list:
        """Frames with seq > `seq` — the exporter's incremental cursor
        (concatenating one process's shipped frames replays its whole
        retained series)."""
        with self._lock:
            return [f for f in self._frames if f["seq"] > int(seq)]

    def names(self) -> list:
        seen = {}
        with self._lock:
            for f in self._frames:
                for k in f["values"]:
                    seen[k] = True
        return sorted(seen)

    def __len__(self) -> int:
        with self._lock:
            return len(self._frames)

    # -- queries --
    def window(self, name, secs=None) -> list:
        """[(t, value)] for `name` over the trailing `secs` (None = the
        whole retained ring), oldest first."""
        name = str(name)
        with self._lock:
            frames = list(self._frames)
        if not frames:
            return []
        cutoff = None if secs is None else frames[-1]["t"] - float(secs)
        out = []
        for f in frames:
            if cutoff is not None and f["t"] < cutoff:
                continue
            v = f["values"].get(name)
            if v is not None:
                out.append((f["t"], v))
        return out

    def latest(self, name):
        w = self.window(name, None)
        return w[-1][1] if w else None

    def rate(self, name, secs) -> float | None:
        """Counter rate over the trailing window, per second.
        COUNTER-AWARE and reset-safe (the Prometheus ``rate()``
        semantic): a sample below its predecessor means the process
        restarted — the post-reset value is the delta, not a negative.
        None when fewer than two samples cover the window."""
        w = self.window(name, secs)
        if len(w) < 2:
            return None
        elapsed = w[-1][0] - w[0][0]
        if elapsed <= 0:
            return None
        total = 0.0
        for (_, prev), (_, cur) in zip(w, w[1:]):
            d = cur - prev
            total += d if d >= 0 else cur
        return total / elapsed

    def derivative(self, name, secs) -> float | None:
        """Gauge slope over the trailing window, units per second —
        least-squares, so one noisy sample can't own the sign (the
        autoscaler's queue-growth predictive input).  None below two
        samples."""
        w = self.window(name, secs)
        if len(w) < 2:
            return None
        t0 = w[0][0]
        n = float(len(w))
        sx = sum(t - t0 for t, _ in w)
        sy = sum(v for _, v in w)
        sxx = sum((t - t0) ** 2 for t, _ in w)
        sxy = sum((t - t0) * v for t, v in w)
        denom = n * sxx - sx * sx
        if denom <= 0:
            return None
        return (n * sxy - sx * sy) / denom

    def ewma(self, name, secs, halflife=None) -> float | None:
        """Time-decayed exponential moving average over the trailing
        window (halflife defaults to secs/4): recent samples dominate
        without a sudden window edge."""
        w = self.window(name, secs)
        if not w:
            return None
        hl = float(halflife) if halflife else max(1e-9, float(secs) / 4.0)
        t_end = w[-1][0]
        num = den = 0.0
        for t, v in w:
            wgt = 0.5 ** ((t_end - t) / hl)
            num += wgt * v
            den += wgt
        return num / den if den > 0 else None

    def series(self, secs=None) -> dict:
        """{name: {"t": [...monotonic...], "wall": [...], "v": [...]}}
        over the trailing window — the /debug/timeseries body."""
        with self._lock:
            frames = list(self._frames)
        if not frames:
            return {}
        cutoff = None if secs is None else frames[-1]["t"] - float(secs)
        out: dict = {}
        for f in frames:
            if cutoff is not None and f["t"] < cutoff:
                continue
            for k, v in f["values"].items():
                s = out.setdefault(k, {"t": [], "wall": [], "v": []})
                s["t"].append(round(f["t"], 6))
                s["wall"].append(round(f["wall"], 6))
                s["v"].append(v)
        return out


# ---------------------------------------------------------------------------
# the registry sampler
# ---------------------------------------------------------------------------

class TimeSeriesSampler(TimeSeries):
    """Snapshot a declared set of registry counters/gauges into the
    ring every `interval_s`.

    A watched name matches its EXACT rendered snapshot key first
    (``engine.tokens``); a bare name with labeled series sums every
    label variant (``serving.requests`` = Σ over status) — the rollup
    shape rates/derivatives want.  Counters win over gauges on a name
    collision (rate() is the counter question).  Each `sample()` also
    publishes the `telemetry.timeseries_samples` health gauge: a
    flat-lined value is the sampler's own outage alarm."""

    def __init__(self, names=(), registry=None, interval_s=None,
                 capacity=None, clock=time.monotonic, name="sampler"):
        super().__init__(capacity=capacity, clock=clock)
        if interval_s is None:
            interval_s = _env_num("PADDLE_TPU_TIMESERIES_INTERVAL_S",
                                  DEFAULT_INTERVAL_S, float)
        self.interval_s = max(0.05, float(interval_s))
        self.watched = tuple(str(n) for n in names)
        self.registry = registry or _metrics.get_registry()
        # the health gauge's label: two samplers in one process (a
        # router AND a server) must not share one gauge, or a dead
        # sampling thread hides behind the live one's count
        self.name = str(name)
        self._samples = 0
        self._kinds: dict = {}     # name -> "counter" | "gauge"
        self._stop = threading.Event()
        self._thread = None

    @staticmethod
    def _resolve(name, table):
        """Exact key, else the sum of the name's label variants; None
        when the table carries neither."""
        v = table.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        prefix = name + "{"
        total, hit = 0.0, False
        for k, tv in table.items():
            if k.startswith(prefix) and isinstance(tv, (int, float)) \
                    and not isinstance(tv, bool):
                total += float(tv)
                hit = True
        return total if hit else None

    def sample(self) -> dict:
        """One sampling pass: resolve every watched name against the
        registry snapshot, record the frame, publish health.  Returns
        the recorded values."""
        snap = self.registry.snapshot()
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        values = {}
        kinds = {}
        for name in self.watched:
            v = self._resolve(name, counters)
            if v is not None:
                kinds[name] = "counter"
            else:
                v = self._resolve(name, gauges)
                if v is not None:
                    kinds[name] = "gauge"
            if v is not None:
                values[name] = v
        self.record(values)
        with self._lock:
            self._samples += 1
            self._kinds.update(kinds)
            n = self._samples
        self.registry.set_gauge("telemetry.timeseries_samples", n,
                                sampler=self.name)
        return values

    def stats(self) -> dict:
        with self._lock:
            n = self._samples
            kinds = dict(self._kinds)
            last = self._frames[-1] if self._frames else None
        return {
            "name": self.name,
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "samples": n,
            "frames": len(self),
            "watched": list(self.watched),
            "kinds": kinds,
            "last_age_s": (None if last is None
                           else round(self.clock() - last["t"], 3)),
        }

    def describe(self, secs=None) -> dict:
        """The /debug/timeseries body: health + full series + a
        convenience rate (COUNTER names only — reset-safe rate() over
        a falling gauge would fabricate positive throughput) and
        derivative (gauge names) per name over the last 30 s."""
        out = dict(self.stats())
        out["series"] = self.series(secs)
        kinds = out["kinds"]
        qsecs = 30.0 if secs is None else float(secs)
        out["rate_30s"] = {
            n: round(r, 6)
            for n in out["series"]
            if kinds.get(n) == "counter"
            and (r := self.rate(n, qsecs)) is not None}
        out["derivative_30s"] = {
            n: round(d, 6)
            for n in out["series"]
            if kinds.get(n) == "gauge"
            and (d := self.derivative(n, qsecs)) is not None}
        return out

    # -- lifecycle --
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle-tpu-timeseries")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard: one bad
                # snapshot pass must not kill the sampling thread)

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)
        self._thread = None


# the process-default sampler: what TelemetryExporter ships frames from
_default_lock = threading.Lock()
_default_sampler = None


def set_default_sampler(sampler, force=False):
    """Register the process's exporter-visible sampler.  First one
    wins unless `force` — a replica process has exactly one server; a
    test harness hosting a router AND a server keeps the first."""
    global _default_sampler
    with _default_lock:
        if _default_sampler is None or force or sampler is None:
            _default_sampler = sampler
        return _default_sampler


def get_default_sampler():
    with _default_lock:
        return _default_sampler


# ---------------------------------------------------------------------------
# per-request latency attribution
# ---------------------------------------------------------------------------

class RequestTimeline:
    """One request's latency story, bounded by construction.

    Events (submitted / admitted / prefill_start / prefill_end /
    evicted / finished) are a short list; token stamps decimate 2×
    whenever they hit the cap (stride doubles, coverage stays
    whole-stream); the top-K largest inter-token gaps keep their exact
    (index, t_before, t_after) — the stall evidence the decision ring
    is queried against."""

    _EVENT_CAP = 64
    # kinds recorded even past the cap (once each, by nature): an
    # eviction-thrashed request — exactly what this endpoint exists to
    # explain — must never show as unfinished because its churn filled
    # the event list first
    _TERMINAL = ("finished",)

    def __init__(self, request_id, clock=time.monotonic,
                 token_cap=None):
        if token_cap is None:
            token_cap = int(_env_num("PADDLE_TPU_ITL_TIMELINE_CAP",
                                     DEFAULT_TIMELINE_CAP, int))
        self.request_id = str(request_id)
        self.clock = clock
        self.token_cap = max(4, int(token_cap))
        self.t0 = float(clock())
        self.wall0 = time.time()
        self._lock = threading.Lock()
        self._events = []          # [(t, kind, data)] — bounded
        self._stamps = []          # [(token_index, t)] — decimated
        self._stride = 1
        self._next_keep = 0
        self.n_tokens = 0
        self.first_token_t = None
        self._last_token_t = None
        self._gap_sum = 0.0
        self._gap_max = 0.0
        self._top_gaps = []        # [(gap_s, idx, t_prev, t_now)] top-K

    def _wall(self, t):
        return self.wall0 + (t - self.t0)

    def event(self, kind, **data) -> None:
        t = float(self.clock())
        kind = str(kind)
        with self._lock:
            if len(self._events) < self._EVENT_CAP \
                    or kind in self._TERMINAL:
                self._events.append((t, kind, dict(data)))
            elif self._events[-1][1] != "events_truncated":
                self._events.append((t, "events_truncated", {}))

    def token(self) -> None:
        """Stamp one accepted token (engine edge)."""
        t = float(self.clock())
        with self._lock:
            idx = self.n_tokens
            self.n_tokens += 1
            if idx == 0:
                self.first_token_t = t
            else:
                gap = t - self._last_token_t
                self._gap_sum += gap
                if gap > self._gap_max:
                    self._gap_max = gap
                self._note_gap_locked(gap, idx, self._last_token_t, t)
            self._last_token_t = t
            if idx >= self._next_keep:
                self._stamps.append((idx, t))
                self._next_keep = idx + self._stride
                if len(self._stamps) > self.token_cap:
                    # decimate: keep every other stamp, double the
                    # stride — memory halves, coverage stays end-to-end
                    self._stamps = self._stamps[::2]
                    self._stride *= 2

    def _note_gap_locked(self, gap, idx, t_prev, t_now):  # pt-lint: ok[PT102] (token holds _lock)
        top = self._top_gaps
        top.append((gap, idx, t_prev, t_now))
        top.sort(reverse=True)
        del top[DEFAULT_TOP_GAPS:]

    def describe(self) -> dict:
        """JSON-ready view: events, decimated stamps, gap stats, and
        the top gaps (each later annotated with co-scheduled decision
        events by `InferenceEngine.request_debug`)."""
        with self._lock:
            events = list(self._events)
            stamps = list(self._stamps)
            top = list(self._top_gaps)
            n = self.n_tokens
            first = self.first_token_t
            gap_sum, gap_max = self._gap_sum, self._gap_max
            stride = self._stride
        return {
            "request_id": self.request_id,
            "wall_start": round(self.wall0, 6),
            "tokens": n,
            "first_token_ms": (None if first is None
                               else round((first - self.t0) * 1e3, 3)),
            "itl_mean_ms": (round(gap_sum / (n - 1) * 1e3, 3)
                            if n > 1 else None),
            "itl_max_ms": round(gap_max * 1e3, 3) if n > 1 else None,
            "events": [{"t": round(t, 6),
                        "wall": round(self._wall(t), 6),
                        "offset_ms": round((t - self.t0) * 1e3, 3),
                        "kind": kind, **data}
                       for t, kind, data in events],
            "token_stamps": [{"token": i, "t": round(t, 6),
                              "offset_ms": round((t - self.t0) * 1e3, 3)}
                             for i, t in stamps],
            "token_stride": stride,
            "gaps": [{"token": idx, "gap_ms": round(g * 1e3, 3),
                      "t_start": round(tp, 6), "t_end": round(tn, 6),
                      "wall_start": round(self._wall(tp), 6)}
                     for g, idx, tp, tn in top],
        }

    def summary(self) -> dict:
        """The tiny per-request row /debug/telemetry and exporter dumps
        embed (full detail stays behind /debug/requests/<id>)."""
        d = self.describe()
        return {k: d[k] for k in ("request_id", "tokens",
                                  "first_token_ms", "itl_mean_ms",
                                  "itl_max_ms")}


# ---------------------------------------------------------------------------
# the scheduler decision ring
# ---------------------------------------------------------------------------

class DecisionRing:
    """Bounded ring of scheduler decisions (admit / evict_recompute /
    prefix_reclaim / defrag), each stamped with the scheduler clock and
    the page pressure at decision time.  `window(t0, t1)` is the
    correlation query behind /debug/requests/<id>: which co-scheduled
    work landed inside THIS token gap."""

    def __init__(self, capacity=512, clock=time.monotonic):
        self.capacity = max(8, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=self.capacity)
        self._seq = 0

    def record(self, kind, **data) -> dict:
        evt = dict(data)
        evt["kind"] = str(kind)
        evt["t"] = float(self.clock())
        evt["wall"] = time.time()
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            self._events.append(evt)
        return evt

    def events(self, limit=None) -> list:
        with self._lock:
            out = list(self._events)
        return out if limit is None else out[-int(limit):]

    def window(self, t0, t1, pad=0.0) -> list:
        lo, hi = float(t0) - float(pad), float(t1) + float(pad)
        with self._lock:
            return [dict(e) for e in self._events if lo <= e["t"] <= hi]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# ---------------------------------------------------------------------------
# online anomaly detection
# ---------------------------------------------------------------------------

class AnomalyDetector:
    """Rolling-baseline latency-regression watchdog.

    Per kind ("ttft", "itl", ...): observations fill a recent window;
    the values the window displaces become the trailing baseline.  When
    the window median exceeds ``ratio ×`` the baseline median (baseline
    mature: ≥ `min_baseline` samples), the detector fires ONCE per
    `cooldown_s`: `telemetry.anomalies{kind}` counter + a loud
    `telemetry.anomaly` flight event carrying both medians.  A cliff
    that persists is eventually absorbed into the baseline and stops
    firing — by then it IS the baseline, and the counter already told
    the story.  Steady noise never fires: medians are robust to
    outliers by construction."""

    def __init__(self, ratio=None, window=None, baseline=128,
                 min_baseline=32, cooldown_s=30.0,
                 clock=time.monotonic):
        if ratio is None:
            ratio = _env_num("PADDLE_TPU_ANOMALY_RATIO", 3.0, float)
        if window is None:
            window = int(_env_num("PADDLE_TPU_ANOMALY_WINDOW", 24, int))
        self.ratio = max(1.0, float(ratio))
        self.window = max(4, int(window))
        self.baseline = max(self.window, int(baseline))
        self.min_baseline = max(4, int(min_baseline))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._state: dict = {}     # kind -> {recent, base, fired, ...}

    def _kind_locked(self, kind):  # pt-lint: ok[PT102] (observe holds _lock)
        st = self._state.get(kind)
        if st is None:
            st = self._state[kind] = {
                "recent": collections.deque(maxlen=self.window),
                "base": collections.deque(maxlen=self.baseline),
                "fired": 0,
                "last_fire_t": None,
                "observed": 0,
            }
        return st

    def observe(self, kind, value_ms) -> bool:
        """Feed one latency observation; returns True when this
        observation fired an anomaly."""
        kind = str(kind)
        v = float(value_ms)
        fire = None
        with self._lock:
            st = self._kind_locked(kind)
            st["observed"] += 1
            recent = st["recent"]
            if len(recent) == recent.maxlen:
                st["base"].append(recent[0])
            recent.append(v)
            if len(recent) < recent.maxlen \
                    or len(st["base"]) < self.min_baseline:
                return False
            med_w = _median(recent)
            med_b = _median(st["base"])
            if med_b is None or med_b <= 0 or med_w <= self.ratio * med_b:
                return False
            now = float(self.clock())
            last = st["last_fire_t"]
            if last is not None and now - last < self.cooldown_s:
                return False
            st["last_fire_t"] = now
            st["fired"] += 1
            fire = (med_w, med_b)
        _metrics.inc("telemetry.anomalies", kind=kind)
        try:
            from . import flight as _flight

            _flight.record("telemetry.anomaly", kind=kind,
                           window_median_ms=round(fire[0], 3),
                           baseline_median_ms=round(fire[1], 3),
                           ratio=round(fire[0] / fire[1], 2))
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: the serving
            # hot path feeds this per token)
        return True

    def report(self) -> dict:
        out = {}
        with self._lock:
            for kind, st in sorted(self._state.items()):
                out[kind] = {
                    "observed": st["observed"],
                    "fired": st["fired"],
                    "window_median_ms": _median(st["recent"]),
                    "baseline_median_ms": _median(st["base"]),
                    "baseline_n": len(st["base"]),
                }
        return out
