"""XLA compile-time cost/memory annotation for the trace timeline.

Every jit compile the framework performs can carry the compiler's OWN
accounting — `Compiled.cost_analysis()` (FLOPs, bytes accessed,
transcendentals) and `Compiled.memory_analysis()` (argument/output/temp
buffer bytes, generated code size) — instead of only the host-side wall
the compile ledger records.  Three consumers per capture:

  * the trace timeline: the `xla.compile:<label>` span that wrapped the
    compile gets the cost dict attached as span args, so clicking a
    compile slice in Perfetto shows what the compiler thought it built;
  * the metrics registry: `xla.cost.*{label=...}` gauges (latest compile
    per label wins — the steady-state executable);
  * the flight recorder: an `xla.compile` event, so a crash dump shows
    the last programs built before the incident;
  * the lifecycle ledger: compile WALL time, split trace+lower vs
    compile (jax folds tracing into `.lower()`, so that is the finest
    split the API exposes), recorded per program label for replica
    cold-start attribution (`lifecycle.compile_ms{program}`) plus an
    `xla.cost.compile_ms{label}` gauge.

`instrument(jitted, label)` wraps a `jax.jit` callable with capture-on-
first-call-per-signature semantics.  When the telemetry stack is off
(neither metrics nor trace enabled) — or when the call is happening
under an outer jax trace (autograd through the dispatch gate hands the
wrapped program Tracers) — the wrapper forwards straight to the jitted
callable: byte-identical behavior to an uninstrumented jit.  When on,
the first call for a new aval signature lowers + AOT-compiles (the same
work `jitted(...)` would do on that call), captures the analysis, and
replays the compiled executable on subsequent calls; any failure in the
AOT path falls back to the plain jitted call.

jax is imported lazily: this module loads during
``paddle_tpu.observability`` import, which must stay stdlib-cheap.
"""
from __future__ import annotations

import threading
import time

from . import flight as _flight
from . import metrics as _metrics
from . import trace as _trace

__all__ = ["analyze_compiled", "capture", "instrument", "last_costs",
           "InstrumentedJit"]

# cost_analysis keys -> snapshot keys (values are floats)
_COST_KEYS = (("flops", "flops"),
              ("bytes accessed", "bytes_accessed"),
              ("transcendentals", "transcendentals"))
# memory_analysis attrs -> snapshot keys (values are ints)
_MEM_KEYS = (("argument_size_in_bytes", "argument_bytes"),
             ("output_size_in_bytes", "output_bytes"),
             ("temp_size_in_bytes", "temp_bytes"),
             ("alias_size_in_bytes", "alias_bytes"),
             ("generated_code_size_in_bytes", "code_bytes"))
# the subset worth a registry gauge per label
_GAUGE_KEYS = ("flops", "bytes_accessed", "temp_bytes", "argument_bytes",
               "output_bytes")

_last: dict = {}
_last_lock = threading.Lock()


def analyze_compiled(compiled, label: str = "jit") -> dict:
    """Cost/memory dict from a `jax.stages.Compiled` (best-effort: every
    backend/version quirk degrades to fewer keys, never an exception)."""
    out = {"label": str(label)}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # one entry per device program
            ca = ca[0] if ca else {}
        if ca:
            for src, dst in _COST_KEYS:
                if src in ca:
                    out[dst] = float(ca[src])
    except Exception as e:
        # degrade to fewer keys, but visibly: a backend whose
        # cost_analysis() suddenly stops answering is a signal (it was
        # the whole r5 MFU-forensics channel), not routine
        _flight.record("xla.cost_analysis_failed", label=str(label),
                       error=type(e).__name__)
    try:
        ma = compiled.memory_analysis()
        for attr, dst in _MEM_KEYS:
            v = getattr(ma, attr, None)
            if v is not None:
                out[dst] = int(v)
    except Exception as e:
        _flight.record("xla.memory_analysis_failed", label=str(label),
                       error=type(e).__name__)
    return out


def capture(compiled, label: str = "jit") -> dict:
    """Analyze `compiled` and fan the result out to gauges + flight (and
    remember it per label for `last_costs`).  Returns the cost dict so
    the caller can also attach it to the surrounding compile span."""
    costs = analyze_compiled(compiled, label)
    for k in _GAUGE_KEYS:
        if k in costs:
            _metrics.set_gauge(f"xla.cost.{k}", costs[k], label=label)
    _flight.record("xla.compile", **costs)
    with _last_lock:
        _last[str(label)] = dict(costs)
    return costs


def last_costs(label=None):
    """Most recent capture for `label`, or the whole {label: costs} map."""
    with _last_lock:
        if label is not None:
            return _last.get(str(label))
        return dict(_last)


def _telemetry_on() -> bool:
    return _metrics.enabled() or _trace.enabled()


def _feed_lifecycle(label, lower_ms, compile_ms) -> None:
    """Attribute a compile to the process lifecycle ledger (replica
    cold-start accounting).  Best-effort: the ledger is observability
    of observability — it must never fail a compile."""
    try:
        from . import lifecycle

        lifecycle.get_ledger().record_compile(label, lower_ms, compile_ms)
    except Exception:  # pt-lint: ok[PT005]
        pass           # (the compile_ms span args above already carry
        # the measurement; a ledger failure must never sink a compile)


# sentinel marking a signature whose compile is in flight on another
# thread (callers fall back to the jitted path until it resolves)
_PENDING = object()


class InstrumentedJit:
    """Wraps a jax.jit callable; first call per aval signature compiles
    AOT inside an `xla.compile:<label>` span and captures cost_analysis.
    Exposes `.lower()` (delegated) so callers that lower-for-analysis
    (DistributedTrainStep.lower) keep working."""

    def __init__(self, jitted, label: str):
        self._jitted = jitted
        self.label = str(label)
        self._compiled: dict = {}
        self._lock = threading.Lock()
        try:
            self.__name__ = getattr(jitted, "__name__", self.label)
        except (AttributeError, TypeError):
            pass  # some wrappers refuse __name__; the label suffices

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def _sig(self, leaves):
        """Hashable aval signature, or None when any leaf isn't a plain
        array (then capture is skipped — a repr-based key could differ
        every call and turn the AOT cache into a compile-per-call).

        Shardings are deliberately NOT in the key: jit outputs fed back
        as inputs (train-step state) carry GSPMDSharding objects that
        hash differently from the NamedSharding the first call was
        placed with even when semantically equal, which would recompile
        the steady-state executable every step.  A genuinely different
        sharding is still safe — the Compiled call rejects it before
        executing and __call__ falls back to the plain jit path."""
        sig = []
        for l in leaves:
            shape = getattr(l, "shape", None)
            dtype = getattr(l, "dtype", None)
            if shape is None or dtype is None:
                return None
            sig.append((tuple(shape), str(dtype),
                        bool(getattr(l, "weak_type", False))))
        return tuple(sig)

    def __call__(self, *args, **kwargs):
        if not _telemetry_on():
            return self._jitted(*args, **kwargs)
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            # under an outer trace (autograd through the dispatch gate):
            # Compiled objects refuse tracers; jit composes fine
            return self._jitted(*args, **kwargs)
        key = self._sig(leaves)
        if key is None:
            return self._jitted(*args, **kwargs)
        # deliberate lock-free fast path: dict membership is GIL-atomic
        # and a stale miss only costs re-entering the claim protocol
        if key not in self._compiled:  # pt-lint: ok[PT102]
            # claim the signature under the lock so concurrent first
            # calls never run the multi-second lower+compile twice;
            # losers (and callers racing the winner) take the plain
            # jitted path, whose own cache dedupes the compile
            with self._lock:
                claimed = key not in self._compiled
                if claimed:
                    self._compiled[key] = _PENDING
            if claimed:
                with _trace.span(f"xla.compile:{self.label}",
                                 cat="compile") as sp:
                    try:
                        # trace+lower vs compile wall split: jax folds
                        # tracing into .lower(), so lower_ms is the
                        # finest trace-side split the API exposes
                        t0 = time.perf_counter()
                        lowered = self._jitted.lower(*args, **kwargs)
                        t1 = time.perf_counter()
                        compiled = lowered.compile()
                        t2 = time.perf_counter()
                        costs = capture(compiled, self.label)
                        costs["lower_ms"] = (t1 - t0) * 1e3
                        costs["compile_ms"] = (t2 - t1) * 1e3
                        _metrics.set_gauge("xla.cost.compile_ms",
                                           costs["compile_ms"],
                                           label=self.label)
                        _feed_lifecycle(self.label, costs["lower_ms"],
                                        costs["compile_ms"])
                        with _last_lock:
                            _last[self.label] = dict(costs)
                        if sp is not None:
                            sp.args.update(costs)
                    except Exception:
                        compiled = None  # permanent fallback for this sig
                # single-writer by the claim protocol above (only the
                # thread that claimed `key` ever stores to it), and a
                # one-slot dict store is GIL-atomic
                self._compiled[key] = compiled  # pt-lint: ok[PT101,PT102]
        entry = self._compiled[key]  # pt-lint: ok[PT102] (GIL-atomic read)
        if entry is None or entry is _PENDING:
            return self._jitted(*args, **kwargs)
        try:
            return entry(*args, **kwargs)
        except (TypeError, ValueError):
            # aval/sharding drift the signature key didn't see: the
            # Compiled rejects the call before executing, so the plain
            # jitted path (which re-specializes) is still safe to run
            return self._jitted(*args, **kwargs)


def instrument(jitted, label: str = "jit"):
    """Wrap a jax.jit callable for compile-cost capture; returns the
    input unchanged when it has no `.lower` (not an AOT-capable stage)."""
    if not hasattr(jitted, "lower"):
        return jitted
    return InstrumentedJit(jitted, label)
