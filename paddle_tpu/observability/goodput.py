"""Training goodput accounting: productive step time vs lost time.

MLPerf-style pod training (PAPERS.md) treats "goodput" — the fraction
of wall-clock a job spends making forward progress — as the scaling
discipline's headline number: a 40% MFU step rate means little if 30%
of the wall went to recompiles, NaN rollbacks, and preemption drains.
This module derives that partition from signals the stack ALREADY
emits — StepTimer records (`step_stats`) and flight-ring events — so
any telemetry run gets a goodput report for free:

  * **productive** — steady-state step walls (records with
    `compile=False`);
  * **compile**    — records flagged `compile=True` (the trace+compile
    ledger);
  * **rollback**   — StepGuard skip/rollback events
    (`resilience.guard_skip` / `resilience.guard_rollback`): each
    skipped step burned ~one median steady step of device time and
    produced nothing;
  * **retry**      — `resilience.retry` flight events carry their
    backoff `delay`; summed, they are wall the job spent waiting to try
    again;
  * **preemption** — `resilience.drain_begin` → `resilience
    .drain_complete`/`drain_timeout` pairs and `preemption.tripped` →
    `preemption.checkpoint_saved` pairs, measured on the flight
    events' own wall timestamps;
  * **other**      — the remainder when the caller supplies the true
    wall (`wall_s=`): time nothing accounted for (input stalls, host
    gaps — the next thing to chase).

`partition()` is pure (synthetic streams test it directly);
`from_live()` reads the default flight recorder; `publish()` exports
`goodput.*` gauges; `metric_rows()` shapes bench-JSON rows for
`tools/perf_gate.py`.  `bench.py --telemetry` embeds the report and
emits the rows.

stdlib-only, package-relative imports guarded (file-loadable).
"""
from __future__ import annotations

import time

__all__ = ["partition", "from_live", "publish", "metric_rows",
           "SCHEMA_VERSION"]

SCHEMA_VERSION = "goodput/v1"

_ROLLBACK_KINDS = ("resilience.guard_skip", "resilience.guard_rollback")
_RETRY_KIND = "resilience.retry"
_DRAIN_PAIRS = (
    ("resilience.drain_begin",
     ("resilience.drain_complete", "resilience.drain_timeout")),
    ("preemption.tripped", ("preemption.checkpoint_saved",)),
)


def _metrics_module():
    try:
        from . import metrics  # type: ignore

        return metrics
    except ImportError:
        return None


def _median(vals):
    if not vals:
        return 0.0
    v = sorted(vals)
    n = len(v)
    return v[n // 2] if n % 2 else (v[n // 2 - 1] + v[n // 2]) / 2.0


def partition(step_records, flight_events=(), wall_s=None) -> dict:
    """Partition wall time.  `step_records` are step_stats dicts
    (StepTimer.records or parsed JSONL); `flight_events` are flight
    ring dicts (wall `t` + `kind`).  `wall_s`, when known, bounds the
    accounting and surfaces unattributed time as `other_s`."""
    recs = [r for r in step_records if isinstance(r, dict)]
    steady = [r for r in recs if not r.get("compile")]
    comp = [r for r in recs if r.get("compile")]

    def total_s(rows):
        return sum(float(r.get("wall_ms", 0.0))
                   * max(int(r.get("n_steps", 1)), 1) for r in rows) / 1e3

    productive_s = total_s(steady)
    compile_s = total_s(comp)
    median_step_s = _median(
        [float(r.get("wall_ms", 0.0)) for r in steady]) / 1e3

    rollback_events = 0
    retry_s = 0.0
    opens: dict = {}
    drain_s = 0.0
    for e in flight_events:
        if not isinstance(e, dict):
            continue
        kind = e.get("kind")
        if kind in _ROLLBACK_KINDS:
            rollback_events += 1
        elif kind == _RETRY_KIND:
            try:
                retry_s += max(0.0, float(e.get("delay", 0.0)))
            except (TypeError, ValueError):
                pass
        else:
            for begin, ends in _DRAIN_PAIRS:
                if kind == begin:
                    opens[begin] = float(e.get("t", 0.0))
                elif kind in ends and begin in opens:
                    t0 = opens.pop(begin)
                    try:
                        drain_s += max(0.0, float(e.get("t", t0)) - t0)
                    except (TypeError, ValueError):
                        pass
    rollback_s = rollback_events * median_step_s

    lost = {"compile_s": round(compile_s, 6),
            "rollback_s": round(rollback_s, 6),
            "retry_s": round(retry_s, 6),
            "preemption_s": round(drain_s, 6)}
    lost_s = sum(lost.values())
    accounted = productive_s + lost_s
    if wall_s is None:
        wall_s = accounted
        other_s = 0.0
    else:
        wall_s = float(wall_s)
        other_s = max(0.0, wall_s - accounted)
    lost["other_s"] = round(other_s, 6)
    lost_s += other_s
    out = {
        "schema": SCHEMA_VERSION,
        "wall_s": round(wall_s, 6),
        "productive_s": round(productive_s, 6),
        "lost_s": round(lost_s, 6),
        "lost": lost,
        "steps": sum(max(int(r.get("n_steps", 1)), 1) for r in steady),
        "compile_records": len(comp),
        "rollback_events": rollback_events,
        "productive_frac": round(productive_s / wall_s, 6)
        if wall_s > 0 else 0.0,
        "lost_frac": round(lost_s / wall_s, 6) if wall_s > 0 else 0.0,
    }
    return out


def from_live(timer, wall_s=None) -> dict:
    """Goodput from a live StepTimer + the default flight recorder —
    what bench.py calls at the end of a telemetry run."""
    try:
        from . import flight as _flight  # type: ignore

        events = _flight.events()
    except ImportError:
        events = ()
    with timer._lock:
        records = list(timer.records)
    return partition(records, events, wall_s=wall_s)


def publish(report, registry=None) -> None:
    """Export a goodput report as `goodput.*` gauges on the shared
    registry (fraction, seconds, and per-category lost seconds) — what
    the telemetry dumps and /metrics carry to the fleet rollup."""
    metrics = _metrics_module()
    if metrics is None:
        return
    reg = registry or metrics.get_registry()
    reg.set_gauge("goodput.productive_frac", report["productive_frac"])
    reg.set_gauge("goodput.productive_s", report["productive_s"])
    reg.set_gauge("goodput.wall_s", report["wall_s"])
    reg.set_gauge("goodput.lost_s", report["lost_s"])
    for cat, v in report.get("lost", {}).items():
        reg.set_gauge("goodput.lost_s", v, category=cat.rsplit("_s", 1)[0])


def metric_rows(report, degraded=False) -> list:
    """Bench-output rows for tools/perf_gate.py: goodput fraction gates
    higher-is-better, lost fraction lower-is-better.  Degraded (CPU
    proxy) runs mark the rows so the gate never judges a proxy
    partition against an on-chip floor."""
    rows = [
        {"metric": "goodput.productive_frac",
         "value": report["productive_frac"], "unit": "frac"},
        {"metric": "goodput.lost_frac", "value": report["lost_frac"],
         "unit": "frac", "lower_better": True},
    ]
    if degraded:
        for r in rows:
            r["degraded"] = True
    return rows


def now_wall_s(t0: float) -> float:
    """Convenience for callers bracketing a run with time.time()."""
    return max(0.0, time.time() - float(t0))
