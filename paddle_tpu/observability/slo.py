"""SLO accounting: per-endpoint latency/availability objectives with a
windowed error-budget burn rate.

The signal ROADMAP item 5 (admission-aware router autoscaling against
latency SLOs) consumes: raw latency histograms say how the server IS
doing; an `SLOTracker` says how it is doing *against what was promised*
— and how fast it is spending the error budget that promise implies.

Model (the SRE-workbook shape, kept deliberately small):

  * an **objective** per endpoint: a latency target (ms) and an
    availability objective (fraction of requests that must succeed,
    e.g. 0.999 → a 0.1% error budget);
  * a sliding **window** of recent request outcomes (t, latency, ok,
    reason) — serving feeds one `observe()` per completed request and
    one `record_shed()` per admission shed (`resilience.shed_requests`
    made visible at the SLO layer, reason label preserved);
  * the **burn rate**: observed error rate over the window divided by
    the error budget.  1.0 = spending budget exactly as fast as the
    objective allows; 14.4 = the classic page-now threshold (a 30-day
    budget gone in ~2 days).  A router can scale on it, a human can
    alert on it.

`report()` returns one JSON-ready dict (embedded in serving's
`GET /debug/telemetry` and in the periodic telemetry dumps the fleet
aggregator rolls up) and publishes `slo.*` gauges on the shared
registry so the burn rate also rides the `/metrics` scrape plane.

stdlib-only; clock injectable so tests drive the window without
sleeping.
"""
from __future__ import annotations

import collections
import threading
import time

__all__ = ["SLOTracker", "SCHEMA_VERSION", "DEFAULT_WINDOW_S"]

SCHEMA_VERSION = "slo/v1"
DEFAULT_WINDOW_S = 300.0

# burn-rate severity rungs (multiples of "exactly on budget"): rendered
# in the report so dashboards and the chaos gate read one field instead
# of re-deriving thresholds
_BURN_FAST = 14.4   # 30-day budget in ~2 days — page
_BURN_SLOW = 3.0    # 30-day budget in ~10 days — ticket


def _metrics_module():
    try:
        from . import metrics  # type: ignore

        return metrics
    except ImportError:
        return None


class _Objective:
    __slots__ = ("latency_target_ms", "availability")

    def __init__(self, latency_target_ms, availability):
        self.latency_target_ms = float(latency_target_ms)
        if not 0.0 < float(availability) < 1.0:
            raise ValueError(
                f"availability objective must be in (0, 1), got "
                f"{availability!r} (1.0 leaves a zero error budget — "
                f"burn rate would be undefined)")
        self.availability = float(availability)


class SLOTracker:
    """Windowed SLO ledger.  Thread-safe (the serving handler threads
    all feed one tracker); bounded (`max_events` per endpoint caps
    memory under sustained overload — the window prune handles the
    normal case)."""

    def __init__(self, window_s=DEFAULT_WINDOW_S, max_events=8192,
                 clock=time.monotonic):
        self.window_s = float(window_s)
        self.max_events = int(max_events)
        self.clock = clock
        self._lock = threading.Lock()
        self._objectives: dict = {}
        # (endpoint, class) -> _Objective: per-class promises (ISSUE
        # 18) — a class without its own objective inherits the
        # endpoint's, so per-class burn is always computable
        self._class_objectives: dict = {}
        self._events: dict = {}    # endpoint -> deque[(t, lat_ms, ok,
        #                            reason, cls)]
        self._totals: dict = {}    # endpoint -> [requests, errors] (lifetime)

    # --- configuration ------------------------------------------------------
    def objective(self, endpoint, latency_target_ms=1000.0,
                  availability=0.999, cls=None):
        """Declare (or replace) the objective for `endpoint`.  With
        `cls`, declare the objective one priority class is promised
        (ISSUE 18) — classes without one inherit the endpoint
        objective.  Returns self so server constructors can chain
        declarations."""
        with self._lock:
            if cls is not None:
                self._class_objectives[(str(endpoint), str(cls))] = \
                    _Objective(latency_target_ms, availability)
            else:
                self._objectives[str(endpoint)] = _Objective(
                    latency_target_ms, availability)
            self._events.setdefault(str(endpoint), collections.deque(
                maxlen=self.max_events))
            self._totals.setdefault(str(endpoint), [0, 0])
        return self

    def endpoints(self):
        with self._lock:
            return sorted(self._objectives)

    # --- feeding ------------------------------------------------------------
    def observe(self, endpoint, latency_ms, ok=True, reason=None,
                cls=None):
        """One finished request: latency in ms (None when the request
        never ran, e.g. a shed), ok=False consumes error budget,
        `reason` labels the failure class in the report, and `cls`
        attributes the outcome to a priority class (ISSUE 18) so the
        report shows WHOSE budget burned."""
        endpoint = str(endpoint)
        now = self.clock()
        with self._lock:
            q = self._events.get(endpoint)
            if q is None:
                q = self._events[endpoint] = collections.deque(
                    maxlen=self.max_events)
                self._totals[endpoint] = [0, 0]
            q.append((now, None if latency_ms is None else float(latency_ms),
                      bool(ok), None if reason is None else str(reason),
                      None if cls is None else str(cls)))
            tot = self._totals[endpoint]
            tot[0] += 1
            if not ok:
                tot[1] += 1
            self._prune_locked(endpoint, now)

    def record_shed(self, endpoint, reason, cls=None):
        """An admission shed: never ran, counts against availability,
        reason label preserved (`shed:queue_full` etc.) so the report
        says WHY the budget burned — the chaos gate asserts on this."""
        self.observe(endpoint, None, ok=False, reason=f"shed:{reason}",
                     cls=cls)

    def _prune_locked(self, endpoint, now):  # pt-lint: ok[PT102] (callers hold _lock)
        q = self._events[endpoint]
        horizon = now - self.window_s
        while q and q[0][0] < horizon:
            q.popleft()

    # --- reporting ----------------------------------------------------------
    def report(self, publish_gauges=True) -> dict:
        """One JSON-ready snapshot: per-endpoint window counts, observed
        availability, burn rate, latency percentiles vs target — plus a
        per-priority-class breakdown (`classes`, ISSUE 18) computed
        against the class objective when one is declared, the endpoint
        objective otherwise.  Also publishes `slo.*{endpoint=...}` (and
        `slo.burn_rate{endpoint=...,class=...}`) gauges unless told not
        to."""
        now = self.clock()
        out = {"schema": SCHEMA_VERSION, "window_s": self.window_s,
               "endpoints": {}}
        metrics = _metrics_module()
        with self._lock:
            endpoints = {ep: (self._objectives.get(ep),
                              list(self._events.get(ep, ())),
                              list(self._totals.get(ep, (0, 0))))
                         for ep in set(self._objectives) | set(self._events)}
            class_objectives = dict(self._class_objectives)
        for ep, (obj, events, totals) in sorted(endpoints.items()):
            events = [e for e in events if e[0] >= now - self.window_s]
            rep = _summarize(events, obj)
            rep["lifetime_requests"] = totals[0]
            rep["lifetime_errors"] = totals[1]
            classes = sorted({e[4] for e in events
                              if len(e) > 4 and e[4]})
            if classes:
                rep["classes"] = {}
                for c in classes:
                    cobj = class_objectives.get((ep, c), obj)
                    crep = _summarize(
                        [e for e in events if len(e) > 4 and e[4] == c],
                        cobj)
                    rep["classes"][c] = crep
                    if publish_gauges and metrics is not None \
                            and "burn_rate" in crep:
                        metrics.set_gauge(
                            "slo.burn_rate", crep["burn_rate"],
                            endpoint=ep, **{"class": c})
            out["endpoints"][ep] = rep
            if publish_gauges and metrics is not None:
                if "burn_rate" in rep:
                    metrics.set_gauge("slo.burn_rate", rep["burn_rate"],
                                      endpoint=ep)
                if "availability" in rep:
                    metrics.set_gauge("slo.availability",
                                      rep["availability"], endpoint=ep)
                metrics.set_gauge("slo.window_requests", rep["requests"],
                                  endpoint=ep)
        return out


def _summarize(events, obj) -> dict:
    """Window stats for one slice of events (an endpoint, or one
    priority class within it) against one objective."""
    n = len(events)
    errors = [e for e in events if not e[2]]
    by_reason: dict = {}
    for e in errors:
        key = e[3] or "error"
        by_reason[key] = by_reason.get(key, 0) + 1
    lats = sorted(e[1] for e in events if e[1] is not None)
    rep = {"requests": n, "errors": len(errors),
           "errors_by_reason": by_reason}
    if n:
        rep["availability"] = round(1.0 - len(errors) / n, 6)
    if lats:
        rep["latency_ms"] = _quantiles(lats)
    if obj is not None:
        budget = 1.0 - obj.availability
        rep["objective"] = {
            "latency_target_ms": obj.latency_target_ms,
            "availability": obj.availability,
            "error_budget": round(budget, 6)}
        if n:
            burn = (len(errors) / n) / budget
            rep["burn_rate"] = round(burn, 4)
            rep["burn_severity"] = (
                "page" if burn >= _BURN_FAST else
                "ticket" if burn >= _BURN_SLOW else "ok")
        if lats:
            within = sum(1 for v in lats
                         if v <= obj.latency_target_ms)
            rep["latency_target_met_frac"] = round(
                within / len(lats), 6)
    return rep


def _quantiles(sorted_lats) -> dict:
    try:
        from .metrics import quantile  # type: ignore
    except ImportError:  # standalone: inline the interpolated-rank math
        def quantile(vals, q):
            n = len(vals)
            pos = q * (n - 1)
            i, frac = int(pos), pos - int(pos)
            if frac == 0.0 or i + 1 >= n:
                return float(vals[min(i, n - 1)])
            return float(vals[i]) + frac * (float(vals[i + 1])
                                            - float(vals[i]))
    return {"p50": round(quantile(sorted_lats, 0.5), 3),
            "p95": round(quantile(sorted_lats, 0.95), 3),
            "p99": round(quantile(sorted_lats, 0.99), 3),
            "max": round(sorted_lats[-1], 3)}
