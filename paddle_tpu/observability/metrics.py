"""Process-local metrics registry: counters / gauges / histograms.

The permanent version of the one-off xprof forensics that drove the
round-5 MFU climb (PERF.md): the hot paths that used to fail *silently*
— flash-attention layout dispatch, autotune cache, `jit.to_static`
retraces, collectives, allocator peaks — increment cheap process-local
metrics, and any run can snapshot them (JSONL or Prometheus text).

Design constraints, in priority order:
  * near-zero cost when disabled: one attribute read + branch per call,
    no dict/lock work.  The registry is DISABLED by default; bench's
    ``--telemetry`` flag, ``observability.attach()``, or env
    ``PADDLE_TPU_METRICS=1`` turn it on.
  * thread-safe when enabled: a single registry lock guards the maps
    (counters are dict updates — contention is negligible next to what
    the instrumented paths do).
  * labels: a metric key is (name, sorted label items).  Snapshot keys
    render as ``name{k=v,...}`` so tests and tools can string-match.
  * scope tagging: while a `profiler.RecordEvent` span is open on this
    thread, HISTOGRAMS observed with ``tag_scope`` enabled (default)
    carry a ``scope=<innermost span>`` label, and flight events / step
    records capture the scope too — "spans tag metrics with the active
    scope".  Counters and gauges are never auto-tagged: their keys stay
    byte-identical to the schema ``attach()`` declares (pass ``scope=``
    explicitly to split one by scope).

This module is stdlib-only on purpose: it imports during
``paddle_tpu.__init__`` (the Pallas dispatch sites pull it in) and must
never create an import cycle or pay a jax import.
"""
from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time

__all__ = [
    "MetricsRegistry", "get_registry", "inc", "set_gauge", "observe",
    "declare", "declare_hist", "snapshot", "to_prometheus",
    "dump_jsonl", "enable", "disable", "enabled", "reset", "push_scope",
    "pop_scope", "current_scope", "DEFAULT_BUCKETS", "quantile",
]

# --------------------------- scope stack ---------------------------

_scopes = threading.local()


def push_scope(name: str) -> int:
    """Enter a named scope on this thread; returns a token for pop_scope
    (tokens make unbalanced exits — e.g. a RecordEvent.end without a
    begin on this thread — safe no-ops instead of corruption)."""
    stack = getattr(_scopes, "stack", None)
    if stack is None:
        stack = _scopes.stack = []
    stack.append(str(name))
    return len(stack)


def pop_scope(token: int) -> None:
    stack = getattr(_scopes, "stack", None)
    if stack and 0 < token <= len(stack):
        del stack[token - 1:]


def current_scope():
    """Innermost open scope name on this thread, or None."""
    stack = getattr(_scopes, "stack", None)
    return stack[-1] if stack else None


# --------------------------- histograms ---------------------------

def _log_spaced(lo: float, hi: float, per_decade: int) -> tuple:
    """Geometric bucket bounds lo..hi, `per_decade` per factor of 10,
    rounded to 4 significant digits so the `le` labels stay short and
    byte-stable across processes (the fleet aggregator merges by
    label)."""
    out = []
    i = 0
    while True:
        b = float(f"{lo * 10 ** (i / per_decade):.4g}")
        if b > hi:
            return tuple(out)
        out.append(b)
        i += 1


# The fixed bucket ladder every histogram uses: 0.1 .. 1e5 covers
# sub-ms serving phases through 100 s compile walls at the ms scale the
# step/request metrics record in.  FIXED (not per-metric) on purpose:
# cross-process histogram merge (tools/telemetry_agg.py) is a plain
# per-bucket sum only when every process shares one ladder.
DEFAULT_BUCKETS = _log_spaced(0.1, 1e5, 4)


def quantile(sorted_vals, q: float):
    """Linear-interpolated quantile of an already-sorted sequence (the
    numpy 'linear' definition): even-count p50 is the midpoint of the
    middle pair, and a 3-sample p95 interpolates instead of snapping to
    the max.  None on empty input."""
    n = len(sorted_vals)
    if n == 0:
        return None
    if n == 1:
        return float(sorted_vals[0])
    pos = max(0.0, min(1.0, float(q))) * (n - 1)
    i = int(pos)
    frac = pos - i
    if frac == 0.0 or i + 1 >= n:
        return float(sorted_vals[min(i, n - 1)])
    return float(sorted_vals[i]) + frac * (
        float(sorted_vals[i + 1]) - float(sorted_vals[i]))


class _Hist:
    """count/sum/min/max, fixed log-spaced buckets (`le`-style: bucket i
    counts values <= bounds[i], the last slot is +Inf overflow), and a
    bounded reservoir of recent values.  Percentiles are exact
    (interpolated ranks over the reservoir) while every observation
    still fits it, and bucket-interpolated beyond that — the buckets
    see ALL observations, so long-running servers report real p99s, not
    the last 256 samples'."""

    __slots__ = ("count", "total", "min", "max", "recent", "bounds",
                 "buckets")

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.recent = collections.deque(maxlen=256)
        self.bounds = bounds
        self.buckets = [0] * (len(bounds) + 1)  # +1: the +Inf slot

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.recent.append(v)
        self.buckets[bisect.bisect_left(self.bounds, v)] += 1

    def percentile(self, q: float):
        """Bucket-interpolated percentile over ALL observations (the
        Prometheus histogram_quantile estimate), clamped to the
        observed [min, max]."""
        if not self.count:
            return None
        target = max(0.0, min(1.0, float(q))) * self.count
        cum = 0
        for i, c in enumerate(self.buckets):
            if c and cum + c >= target:
                lo = self.min if i == 0 else self.bounds[i - 1]
                hi = self.max if i >= len(self.bounds) else self.bounds[i]
                est = lo + (hi - lo) * ((target - cum) / c)
                return max(self.min, min(self.max, est))
            cum += c
        return self.max

    def summary(self) -> dict:
        out = {"count": self.count, "total": round(self.total, 6)}
        if self.count:
            out["mean"] = round(self.total / self.count, 6)
            out["min"] = round(self.min, 6)
            out["max"] = round(self.max, 6)
            if self.count <= len(self.recent):
                # the reservoir still holds every observation: exact
                # interpolated-rank percentiles
                r = sorted(self.recent)
                p50, p95, p99 = (quantile(r, q)
                                 for q in (0.5, 0.95, 0.99))
            else:
                p50, p95, p99 = (self.percentile(q)
                                 for q in (0.5, 0.95, 0.99))
            out["p50"] = round(p50, 6)
            out["p95"] = round(p95, 6)
            out["p99"] = round(p99, 6)
            out["last"] = round(self.recent[-1], 6)
            # sparse non-cumulative bucket counts keyed by upper bound
            # ("inf" = overflow): what telemetry_agg sums to merge one
            # fleet-wide distribution
            out["buckets"] = {
                ("inf" if i >= len(self.bounds)
                 else f"{self.bounds[i]:g}"): c
                for i, c in enumerate(self.buckets) if c}
        return out


# --------------------------- registry ---------------------------

def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render(name: str, lkey: tuple) -> str:
    if not lkey:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in lkey) + "}"


class MetricsRegistry:
    def __init__(self, enabled: bool = False, tag_scope: bool = True):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._hists: dict = {}
        self._enabled = bool(enabled)
        self.tag_scope = tag_scope

    # -- state --
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- recording --
    def _tagged(self, labels: dict) -> dict:
        # auto-scope-tagging applies to HISTOGRAMS only (timings are
        # scope-local by nature; RecordEvent integration) — see inc()
        if self.tag_scope and "scope" not in labels:
            s = current_scope()
            if s is not None:
                labels = dict(labels, scope=s)
        return labels

    def inc(self, name: str, value=1, **labels) -> None:
        # counters are NOT auto-scope-tagged: their keys must stay
        # byte-identical to the schema attach() declares (pass scope=
        # explicitly to split a counter by scope)
        if not self._enabled:
            return
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def declare(self, name: str, **labels) -> None:
        """Pre-register a counter at 0 so snapshots carry a stable schema
        even for paths that never fired this run (e.g. autotune on a CPU
        host).  Works regardless of the enabled flag — declaring schema
        is not a hot path."""
        key = (name, _label_key(labels))
        with self._lock:
            self._counters.setdefault(key, 0)

    def declare_hist(self, name: str, **labels) -> None:
        """Pre-register an EMPTY histogram (count 0, full bucket ladder)
        so snapshots and /metrics render the series before the first
        observation — a fresh server exposes `serving.itl_ms` at zero
        instead of omitting it (ISSUE 15 schema discipline).  Works
        regardless of the enabled flag, like declare()."""
        key = (name, _label_key(labels))
        with self._lock:
            self._hists.setdefault(key, _Hist())

    def set_gauge(self, name: str, value, **labels) -> None:
        if not self._enabled:
            return
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value, **labels) -> None:
        if not self._enabled:
            return
        key = (name, _label_key(self._tagged(labels)))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.observe(value)

    # -- export --
    def snapshot(self) -> dict:
        """One structured dict: {"ts", "counters", "gauges", "histograms"}
        with ``name{k=v}`` string keys (JSON-serializable as-is)."""
        with self._lock:
            counters = {_render(n, l): v
                        for (n, l), v in sorted(self._counters.items())}
            gauges = {_render(n, l): v
                      for (n, l), v in sorted(self._gauges.items())}
            hists = {_render(n, l): h.summary()
                     for (n, l), h in sorted(self._hists.items())}
        return {"ts": time.time(), "counters": counters, "gauges": gauges,
                "histograms": hists}

    def to_prometheus(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus text exposition format: counters, gauges, and full
        histograms — cumulative ``_bucket{le="..."}`` series (the
        ``histogram_quantile()`` input), ``_sum``/``_count``, plus a
        separate ``<name>_quantile{quantile="..."}`` gauge family
        carrying the registry's own p50/p95/p99 so a bare curl shows
        the percentiles without a PromQL engine.  (A distinct family on
        purpose: bare-name ``{quantile=}`` samples inside a ``# TYPE
        ... histogram`` block are invalid under OpenMetrics/strict
        parsers and would poison the whole scrape.)"""
        def pname(name):
            return prefix + "_" + name.replace(".", "_").replace("-", "_")

        def plabels(lkey, *extra):
            items = list(lkey) + list(extra)
            if not items:
                return ""
            return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"

        lines = []
        with self._lock:
            seen = set()
            for (n, l), v in sorted(self._counters.items()):
                if n not in seen:
                    lines.append(f"# TYPE {pname(n)} counter")
                    seen.add(n)
                lines.append(f"{pname(n)}{plabels(l)} {v}")
            for (n, l), v in sorted(self._gauges.items()):
                if n not in seen:
                    lines.append(f"# TYPE {pname(n)} gauge")
                    seen.add(n)
                lines.append(f"{pname(n)}{plabels(l)} {v}")
            for (n, l), h in sorted(self._hists.items()):
                if n not in seen:
                    lines.append(f"# TYPE {pname(n)} histogram")
                    seen.add(n)
                cum = 0
                for i, b in enumerate(h.bounds):
                    cum += h.buckets[i]
                    lines.append(f"{pname(n)}_bucket"
                                 f"{plabels(l, ('le', f'{b:g}'))} {cum}")
                lines.append(f"{pname(n)}_bucket"
                             f"{plabels(l, ('le', '+Inf'))} {h.count}")
                lines.append(f"{pname(n)}_sum{plabels(l)} {h.total}")
                lines.append(f"{pname(n)}_count{plabels(l)} {h.count}")
                summ = h.summary()
                qname = pname(n) + "_quantile"
                if qname not in seen and any(
                        f"p{int(float(q) * 100)}" in summ
                        for q in ("0.5", "0.95", "0.99")):
                    lines.append(f"# TYPE {qname} gauge")
                    seen.add(qname)
                for q in ("0.5", "0.95", "0.99"):
                    key = "p" + str(int(float(q) * 100))
                    if key in summ:
                        lines.append(
                            f"{qname}{plabels(l, ('quantile', q))} "
                            f"{summ[key]}")
        return "\n".join(lines) + "\n"

    def dump_jsonl(self, path: str, extra: dict | None = None) -> str:
        """Append one snapshot line to `path` (the chip-session-log
        convention: one self-describing JSON object per line)."""
        line = {"phase": "metrics_snapshot",
                "t": time.strftime("%Y-%m-%dT%H:%M:%S")}
        if extra:
            line.update(extra)
        line.update(self.snapshot())
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(line, default=str) + "\n")
        return path


_default = MetricsRegistry(
    enabled=os.environ.get("PADDLE_TPU_METRICS", "0") in ("1", "true",
                                                          "True"))


def get_registry() -> MetricsRegistry:
    return _default


# module-level conveniences bound to the default registry — the form the
# instrumented call sites use (`metrics.inc("flash.dispatch", tier=...)`)
def inc(name, value=1, **labels):
    _default.inc(name, value, **labels)


def declare(name, **labels):
    _default.declare(name, **labels)


def declare_hist(name, **labels):
    _default.declare_hist(name, **labels)


def set_gauge(name, value, **labels):
    _default.set_gauge(name, value, **labels)


def observe(name, value, **labels):
    _default.observe(name, value, **labels)


def snapshot():
    return _default.snapshot()


def to_prometheus(prefix="paddle_tpu"):
    return _default.to_prometheus(prefix)


def dump_jsonl(path, extra=None):
    return _default.dump_jsonl(path, extra)


def enable():
    _default.enable()


def disable():
    _default.disable()


def enabled():
    return _default.enabled()


def reset():
    _default.reset()
