"""Step-level training/serving telemetry: StepTimer + JSONL stream.

A `StepTimer` sits in the train/serve loop (and `bench.py --telemetry`)
and turns wall-clock step measurements into:

  * per-step records — wall time, tokens/s, estimated MFU (from the
    caller's FLOPs accounting, the same 6*N*tokens model bench.py uses),
    host->device transfer bytes, device allocator peak — emitted as a
    JSONL stream whose lines follow the `tools/chip_session_log.jsonl`
    convention (every line a self-describing object with "phase" and
    "t"), so `tools/analyze_chip_log.py` consumes live runs and
    historical logs uniformly;
  * a compile-time ledger: records marked ``compile=True`` (first-step
    trace+compile walls) are summarized separately from steady-state
    steps, making "first step 38 s, steady 210 ms" a queryable fact
    instead of an xprof anecdote;
  * registry metrics: `step.wall_ms` / `step.compile_ms` histograms and
    `mem.peak_bytes_in_use` gauges on the shared metrics registry.

Schema (`step_stats/v1`) — one line per record:
    {"phase": "step_stats", "t": "<ISO8601>", "run_id": str,
     "step": int, "n_steps": int, "wall_ms": float, "compile": bool,
     optional: "tokens_per_s", "mfu", "transfer_bytes",
               "peak_bytes_in_use", "scope"}

This module keeps its top level stdlib-only AND free of package-relative
imports: `tools/analyze_chip_log.py` file-loads it so the log analyzer
works without importing (jax-heavy) `paddle_tpu`.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["StepTimer", "STEP_PHASE", "SCHEMA_VERSION", "validate_stream",
           "summarize_stream", "add_record_hook", "remove_record_hook"]

STEP_PHASE = "step_stats"
SCHEMA_VERSION = "step_stats/v1"

_REQUIRED = {"phase": str, "t": str, "run_id": str, "step": int,
             "n_steps": int, "wall_ms": (int, float), "compile": bool}
_OPTIONAL = {"tokens_per_s": (int, float), "mfu": (int, float),
             "transfer_bytes": int, "peak_bytes_in_use": int,
             "scope": str}


def _iso_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S")


def _obs_modules():
    """(metrics, flight, trace) from the observability package, or Nones
    when running standalone (file-loaded by tools/)."""
    try:
        from . import flight, metrics, trace  # type: ignore

        return metrics, flight, trace
    except ImportError:
        return None, None, None


# record hooks: callables invoked with each finished step record —
# how the resilience watchdog heartbeats off step progress without
# step_stats importing resilience (no cycle, no per-site wiring)
_record_hooks: list = []


def add_record_hook(fn) -> None:
    if fn not in _record_hooks:
        _record_hooks.append(fn)


def remove_record_hook(fn) -> None:
    if fn in _record_hooks:
        _record_hooks.remove(fn)


def _device_peak_bytes():
    """Allocator high-watermark from the PJRT backend; None when the
    backend doesn't report (CPU) or paddle_tpu isn't importable."""
    try:
        from paddle_tpu import device as _device

        v = _device.max_memory_allocated()
        return int(v) if v else None
    except Exception:
        return None


class StepTimer:
    """Feed it step walls; it emits records, metrics, and a summary.

    tokens_per_step / flops_per_step / peak_flops may be set after
    construction (bench knows the parameter count only after building
    the model) — rates appear on records from that point on.
    """

    def __init__(self, run_id=None, tokens_per_step=None,
                 flops_per_step=None, peak_flops=None, sink=None,
                 read_device_memory=True):
        self.run_id = str(run_id) if run_id else f"run_{os.getpid()}"
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        self.read_device_memory = read_device_memory
        self._sink_path = sink
        self.records: list = []
        self._lock = threading.Lock()
        self._next_step = 0

    @contextlib.contextmanager
    def step(self, n_steps=1, compile_step=False, transfer_bytes=0):
        """Context manager timing one step (or one n_steps-long compiled
        multi-step program — the wall is divided per step)."""
        t0 = time.perf_counter()
        yield
        self.record(time.perf_counter() - t0, n_steps=n_steps,
                    compile_step=compile_step,
                    transfer_bytes=transfer_bytes)

    def record(self, wall_s, n_steps=1, compile_step=False,
               transfer_bytes=0):
        """Record a measured wall of `n_steps` device steps."""
        n = max(int(n_steps), 1)
        per_step_s = float(wall_s) / n
        metrics, _flight, trace = _obs_modules()
        rec = {"phase": STEP_PHASE, "t": _iso_now(), "run_id": self.run_id,
               "step": -1, "n_steps": n,
               "wall_ms": round(per_step_s * 1e3, 4),
               "compile": bool(compile_step)}
        if transfer_bytes:
            rec["transfer_bytes"] = int(transfer_bytes)
        if self.tokens_per_step and not compile_step:
            rec["tokens_per_s"] = round(self.tokens_per_step / per_step_s, 2)
            if self.flops_per_step and self.peak_flops:
                rec["mfu"] = round(self.flops_per_step / per_step_s
                                   / self.peak_flops, 6)
        if self.read_device_memory:
            peak = _device_peak_bytes()
            if peak is not None:
                rec["peak_bytes_in_use"] = peak
        if metrics is not None:
            scope = metrics.current_scope()
            if scope is not None:
                rec["scope"] = scope
            name = "step.compile_ms" if compile_step else "step.wall_ms"
            metrics.observe(name, per_step_s * 1e3, run_id=self.run_id)
            if "peak_bytes_in_use" in rec:
                metrics.set_gauge("mem.peak_bytes_in_use",
                                  rec["peak_bytes_in_use"])
            if transfer_bytes:
                metrics.inc("step.transfer_bytes", int(transfer_bytes),
                            run_id=self.run_id)
        with self._lock:
            # step id claimed under the lock: concurrent record() calls
            # must not share an id (the JSONL stream keys on it)
            rec["step"] = self._next_step
            self._next_step += n
            self.records.append(rec)
        if trace is not None and trace.enabled():
            # frame marker on the run's synthetic track: the step just
            # finished, so it occupies [now - wall, now] on the timeline
            name = "compile+step" if compile_step else (
                f"step {rec['step']}" if n == 1
                else f"steps {rec['step']}..{rec['step'] + n - 1}")
            trace.frame(name, float(wall_s) * 1e6,
                        track=f"steps:{self.run_id}",
                        step=rec["step"], n_steps=n,
                        wall_ms=rec["wall_ms"],
                        compile=bool(compile_step))
            if "peak_bytes_in_use" in rec:
                trace.counter("mem.peak_bytes_in_use",
                              track=f"mem:{self.run_id}",
                              bytes=rec["peak_bytes_in_use"])
        if self._sink_path:
            try:
                d = os.path.dirname(os.path.abspath(self._sink_path))
                os.makedirs(d, exist_ok=True)
                with open(self._sink_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass  # telemetry must never sink the run
        for hook in list(_record_hooks):
            try:
                hook(rec)
            except Exception:
                # a broken hook must never sink the run — but a hook
                # that dies silently (a dead watchdog heartbeat!) is
                # exactly the failure the metrics exist to surface
                if metrics is not None:
                    metrics.inc("step.record_hook_errors")
        return rec

    def summary(self) -> dict:
        """Aggregate view for embedding (bench JSON `telemetry.step_stats`):
        compile ledger vs steady-state wall stats, throughput, MFU."""
        with self._lock:
            recs = list(self.records)
        steady = [r for r in recs if not r["compile"]]
        comp = [r for r in recs if r["compile"]]
        out = {"schema": SCHEMA_VERSION, "run_id": self.run_id,
               "records": len(recs),
               "steps": sum(r["n_steps"] for r in recs)}
        if comp:
            walls = [r["wall_ms"] * r["n_steps"] for r in comp]
            out["compile_ms"] = {"count": len(comp),
                                 "total": round(sum(walls), 3),
                                 "max": round(max(walls), 3)}
        if steady:
            walls = sorted(r["wall_ms"] for r in steady)
            out["wall_ms"] = {
                "count": len(walls),
                "mean": round(sum(walls) / len(walls), 4),
                "min": round(walls[0], 4), "max": round(walls[-1], 4),
                "p50": round(walls[len(walls) // 2], 4)}
            total_steps = sum(r["n_steps"] for r in steady)
            total_s = sum(r["wall_ms"] * r["n_steps"] for r in steady) / 1e3
            if self.tokens_per_step and total_s > 0:
                out["tokens_per_s"] = round(
                    self.tokens_per_step * total_steps / total_s, 2)
                if self.flops_per_step and self.peak_flops:
                    out["mfu"] = round(
                        self.flops_per_step * total_steps / total_s
                        / self.peak_flops, 6)
        tb = sum(r.get("transfer_bytes", 0) for r in recs)
        if tb:
            out["transfer_bytes"] = tb
        peaks = [r["peak_bytes_in_use"] for r in recs
                 if "peak_bytes_in_use" in r]
        if peaks:
            out["peak_bytes_in_use"] = max(peaks)
        return out


# ----------------------- stream validation -----------------------
#
# Pure functions over parsed JSONL entries (tools/analyze_chip_log.py
# file-loads this module to get them — keep them stdlib-only).

def validate_stream(entries) -> list:
    """Schema errors for the step_stats entries in `entries` (non-step
    entries are ignored — chip_session logs interleave phases).  Empty
    list = valid."""
    errors = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or e.get("phase") != STEP_PHASE:
            continue
        for key, typ in _REQUIRED.items():
            if key not in e:
                errors.append(f"entry {i}: missing required key {key!r}")
            elif not isinstance(e[key], typ) or isinstance(e[key], bool) \
                    and typ is not bool:
                errors.append(
                    f"entry {i}: key {key!r} has type "
                    f"{type(e[key]).__name__}, expected {typ}")
        for key, typ in _OPTIONAL.items():
            if key in e and not isinstance(e[key], typ):
                errors.append(
                    f"entry {i}: optional key {key!r} has type "
                    f"{type(e[key]).__name__}, expected {typ}")
        if isinstance(e.get("wall_ms"), (int, float)) and e["wall_ms"] < 0:
            errors.append(f"entry {i}: negative wall_ms")
    return errors


def summarize_stream(entries) -> dict:
    """Per-run_id digest of a step_stats stream: step counts, compile vs
    steady wall stats, mean throughput/MFU.  Shape:
    {run_id: {"records", "steps", "compile_ms_total", "steady_wall_ms":
    {...}, "tokens_per_s"?, "mfu"?}}"""
    runs: dict = {}
    for e in entries:
        if not isinstance(e, dict) or e.get("phase") != STEP_PHASE:
            continue
        runs.setdefault(e.get("run_id", "?"), []).append(e)
    out = {}
    for run_id, recs in runs.items():
        steady = [r for r in recs if not r.get("compile")]
        comp = [r for r in recs if r.get("compile")]
        s = {"records": len(recs),
             "steps": sum(int(r.get("n_steps", 1)) for r in recs),
             "compile_ms_total": round(
                 sum(float(r.get("wall_ms", 0)) * int(r.get("n_steps", 1))
                     for r in comp), 3)}
        if steady:
            walls = sorted(float(r.get("wall_ms", 0)) for r in steady)
            s["steady_wall_ms"] = {
                "count": len(walls),
                "mean": round(sum(walls) / len(walls), 4),
                "min": round(walls[0], 4), "max": round(walls[-1], 4)}
            tps = [r["tokens_per_s"] for r in steady if "tokens_per_s" in r]
            if tps:
                s["tokens_per_s_mean"] = round(sum(tps) / len(tps), 2)
            mfus = [r["mfu"] for r in steady if "mfu" in r]
            if mfus:
                s["mfu_mean"] = round(sum(mfus) / len(mfus), 6)
        out[run_id] = s
    return out
