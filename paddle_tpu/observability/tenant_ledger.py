"""Per-tenant metering: a bounded-cardinality resource ledger.

ROADMAP item 4 (multi-tenant QoS) needs the stack to answer "which
tenant consumed what" before any priority/quota policy can exist.  The
population is millions of tenants (PAPERS.md's shared-prefix serving
workload), so per-tenant METRIC LABELS are a cardinality bomb — one
`tenant.requests{tenant=...}` counter per distinct id would grow the
registry (and every Prometheus scrape) without bound.  This module is
the alternative: a `TenantLedger` tracks the top-K heavy hitters
EXACTLY like Space-Saving [Metwally et al. 2005] tracks frequencies,
folds everyone else into an honest `~other` bucket, and maintains an
explicit conservation invariant:

    for every metered field:  Σ tracked tenants + other == totals

so a million distinct tenants cost O(K) memory and the books still
balance to the global counters.  What is metered, per tenant:

  * `requests`              by status (ok / shed / client_error / error)
  * `prefill_tokens`        prompt tokens actually computed at prefill
  * `prefill_saved_tokens`  prompt tokens served from the prefix cache
                            instead (PR 13's hits, attributed to the
                            tenants they benefit)
  * `decode_tokens`         accepted decode tokens
  * `decode_slot_ms`        wall-milliseconds of decode-slot occupancy
  * `kv_page_seconds`       ∫ page_count dt over each sequence's
                            residency (admission → eviction/release)

Space-Saving semantics: the table holds at most K entries.  A new
tenant arriving at a full table REPLACES the minimum-weight entry; the
newcomer inherits the victim's weight as its over-estimate bound
(`err`), and the victim's EXACT counts fold into `~other` — so counts
conserve (nothing is dropped), while `weight`/`err` carry the classic
top-K guarantee (any tenant with true weight > err is in the table).
`weight` grows by 1 per request + 1 per token, the units the ledger
exists to attribute.

Engine-token coherence: `record_decode()` increments the global
`engine.tokens` counter INSIDE the ledger lock (the call site skips
its own increment when a ledger is wired), and `snapshot()` reads the
counter back under the same lock — so a snapshot's
`metrics_engine_tokens` is EXACTLY consistent with its
`totals.decode_tokens` even while tokens stream (the chaos
conservation gate compares the two; a mid-dump race can never skew
them).  The field equals `totals.decode_tokens` only when this ledger
is the process's sole decode biller (one engine per process — the
replica deployment).

Aggregate (bounded-label) metrics: `record_request` also counts
`tenant.requests{status=...}` on the shared registry, and `snapshot`
publishes `tenant.tracked` / `tenant.other_tokens` gauges — the ONLY
tenant data that ever reaches `/metrics`.  The top-K table itself is
served by `GET /debug/tenants` and the telemetry dumps, never rendered
to Prometheus.

Knobs:
  PADDLE_TPU_TENANT_LEDGER   "0" disables metering entirely    (on)
  PADDLE_TPU_TENANT_TOPK     table capacity K                  (32)

stdlib-only and file-loadable standalone (the `_obs_modules` guard, as
export.py): `tools/telemetry_agg.py` file-loads this module for
`merge_snapshots` — merging two Space-Saving sketches sums matched
keys and folds unmatched evictees into error bounds / `~other`.
"""
from __future__ import annotations

import os
import re
import threading
from collections import deque

__all__ = [
    "TenantLedger", "merge_snapshots", "conservation_delta",
    "sanitize_tenant", "enabled", "topk", "SCHEMA_VERSION",
    "ANON_TENANT", "OTHER_KEY", "STATUSES", "COUNT_FIELDS",
    "FLOAT_FIELDS",
]

SCHEMA_VERSION = "tenant_ledger/v1"
ANON_TENANT = "anon"
OTHER_KEY = "~other"
DEFAULT_TOPK = 32
RESERVOIR = 64

# request outcomes the ledger books (serving's `timeout` maps to
# `error` at the billing site: a deadline burn is the server's failure)
STATUSES = ("ok", "shed", "client_error", "error")
# integer token fields + float resource fields — every snapshot/merge/
# conservation helper iterates these, so adding a metered quantity is
# one tuple edit
COUNT_FIELDS = ("prefill_tokens", "prefill_saved_tokens",
                "decode_tokens")
FLOAT_FIELDS = ("decode_slot_ms", "kv_page_seconds")

# tenant ids ride HTTP headers, JSON dumps and debug tables: same
# hostile-input discipline as request ids (request_trace._REQUEST_ID)
_TENANT_ID = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")


def _metrics_module():
    """The metrics sibling, or None when file-loaded standalone."""
    try:
        from . import metrics  # type: ignore

        return metrics
    except ImportError:
        return None


def sanitize_tenant(raw):
    """A safe tenant id, or None when `raw` is absent/hostile."""
    if raw is None:
        return None
    s = str(raw)
    return s if _TENANT_ID.match(s) else None


def enabled() -> bool:
    """Metering is on unless PADDLE_TPU_TENANT_LEDGER=0.  Callers that
    construct ledgers additionally require the metrics registry to be
    live (a detached process must not pay even O(K))."""
    return os.environ.get("PADDLE_TPU_TENANT_LEDGER", "1") \
        not in ("0", "off", "false")


def topk() -> int:
    try:
        k = int(os.environ.get("PADDLE_TPU_TENANT_TOPK", DEFAULT_TOPK))
    except ValueError:
        k = DEFAULT_TOPK
    return max(1, k)


def _new_entry(weight=0.0, err=0.0):
    return {
        "requests": dict.fromkeys(STATUSES, 0),
        "prefill_tokens": 0, "prefill_saved_tokens": 0,
        "decode_tokens": 0,
        "decode_slot_ms": 0.0, "kv_page_seconds": 0.0,
        "weight": float(weight), "err": float(err),
        # per-tenant latency reservoirs (top-K only): sliding windows,
        # summarized (never dumped raw) — an evicted tenant's window
        # is dropped, its counts fold into ~other
        "_ttft": deque(maxlen=RESERVOIR),
        "_itl": deque(maxlen=RESERVOIR),
    }


def _fold(dst, src):
    """Fold one entry/bucket's exact counts into another (eviction and
    merge both route through here — conservation by construction)."""
    for s, n in src["requests"].items():
        dst["requests"][s] = dst["requests"].get(s, 0) + int(n)
    for f in COUNT_FIELDS:
        dst[f] = dst.get(f, 0) + int(src.get(f, 0))
    for f in FLOAT_FIELDS:
        dst[f] = dst.get(f, 0.0) + float(src.get(f, 0.0))
    return dst


def _summary(vals):
    """p50/p95/max/n over a small reservoir (shared quantile helper
    when the metrics sibling is importable, else local interpolation)."""
    vals = sorted(float(v) for v in vals)
    if not vals:
        return None
    m = _metrics_module()
    if m is not None:
        q = m.quantile
    else:
        def q(sv, p):
            pos = p * (len(sv) - 1)
            i, frac = int(pos), pos - int(pos)
            if frac == 0.0 or i + 1 >= len(sv):
                return float(sv[min(i, len(sv) - 1)])
            return float(sv[i]) + frac * (float(sv[i + 1])
                                          - float(sv[i]))
    return {"p50": round(q(vals, 0.5), 3), "p95": round(q(vals, 0.95), 3),
            "max": round(vals[-1], 3), "n": len(vals)}


class TenantLedger:
    """Bounded top-K tenant accounting (see module docstring).

    Thread-safe; every mutator is O(1) amortized except the O(K) min
    scan on an eviction (K is small by design).  One instance per
    engine/server/router — NOT process-global, so in-process
    multi-replica tests keep per-replica books."""

    def __init__(self, k=None, clock=None):
        self.k = int(k) if k else topk()
        self._lock = threading.Lock()
        self._tenants: dict = {}
        self._other = _new_entry()
        self._other_folds = 0     # evictions folded into ~other
        self._totals = _new_entry()
        self._distinct_seen = 0   # distinct ids ever admitted

    # --- recording ---------------------------------------------------------
    def _entry(self, tenant):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
        """The tracked entry for `tenant`, admitting (and possibly
        evicting) per Space-Saving.  Caller holds the lock."""
        e = self._tenants.get(tenant)
        if e is not None:
            return e
        self._distinct_seen += 1
        if len(self._tenants) < self.k:
            return self._tenants.setdefault(tenant, _new_entry())
        victim_id = min(self._tenants,
                        key=lambda t: self._tenants[t]["weight"])
        victim = self._tenants.pop(victim_id)
        _fold(self._other, victim)
        self._other_folds += 1
        # Space-Saving: the newcomer inherits the victim's weight as
        # its over-estimate bound; its COUNTS start at zero (they were
        # genuinely not observed — the bound `err` says how much of
        # `weight` may be inherited, not earned)
        e = _new_entry(weight=victim["weight"], err=victim["weight"])
        self._tenants[tenant] = e
        return e

    def _charge(self, tenant, winc):
        e = self._entry(tenant)
        e["weight"] += winc
        return e

    def record_request(self, tenant, status):
        """Bill one request outcome.  Unknown statuses map to `error`;
        `timeout` maps to `error` (the bounded-status discipline)."""
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        status = str(status)
        if status == "timeout" or status not in STATUSES:
            status = "error"
        with self._lock:
            e = self._charge(tenant, 1.0)
            e["requests"][status] += 1
            self._totals["requests"][status] += 1
        m = _metrics_module()
        if m is not None:
            # the aggregate (bounded-label) mirror on the registry
            m.inc("tenant.requests", status=status)

    def record_prefill(self, tenant, computed, saved=0):
        """Bill prefill work: `computed` prompt tokens actually ran the
        model, `saved` were served from the prefix cache instead."""
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        computed, saved = max(0, int(computed)), max(0, int(saved))
        with self._lock:
            e = self._charge(tenant, float(computed + saved))
            e["prefill_tokens"] += computed
            e["prefill_saved_tokens"] += saved
            self._totals["prefill_tokens"] += computed
            self._totals["prefill_saved_tokens"] += saved

    def record_decode(self, tenant, n=1, count_engine_tokens=True):
        """Bill `n` accepted decode tokens.  When the metrics registry
        is live this ALSO increments `engine.tokens` inside the ledger
        lock (the call site must then skip its own inc): the pairing is
        what makes a concurrent snapshot's `metrics_engine_tokens`
        exactly consistent with `totals.decode_tokens`."""
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        n = int(n)
        if n <= 0:
            return
        m = _metrics_module()
        with self._lock:
            e = self._charge(tenant, float(n))
            e["decode_tokens"] += n
            self._totals["decode_tokens"] += n
            if count_engine_tokens and m is not None:
                m.inc("engine.tokens", n)

    def record_decode_slot_ms(self, tenant, ms):
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        ms = float(ms)
        if ms <= 0.0:
            return
        with self._lock:
            # no weight charge: slot-ms is derived occupancy, not a
            # new unit of demand (requests/tokens already charged it)
            e = self._entry(tenant)
            e["decode_slot_ms"] += ms
            self._totals["decode_slot_ms"] += ms

    def record_page_seconds(self, tenant, page_seconds):
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        ps = float(page_seconds)
        if ps <= 0.0:
            return
        with self._lock:
            e = self._entry(tenant)
            e["kv_page_seconds"] += ps
            self._totals["kv_page_seconds"] += ps

    def observe_ttft(self, tenant, ms):
        """Per-tenant TTFT sample — stored ONLY while the tenant is in
        the top-K table (reservoirs are bounded to K by construction;
        an untracked tenant's sample is deliberately dropped, never a
        reason to admit it)."""
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        with self._lock:
            e = self._tenants.get(tenant)
            if e is not None:
                e["_ttft"].append(float(ms))

    def observe_itl(self, tenant, ms):
        tenant = sanitize_tenant(tenant) or ANON_TENANT
        with self._lock:
            e = self._tenants.get(tenant)
            if e is not None:
                e["_itl"].append(float(ms))

    # --- reading -----------------------------------------------------------
    @staticmethod
    def _entry_out(e, latencies=True):
        out = {"requests": {s: n for s, n in e["requests"].items()
                            if n},
               "weight": round(float(e["weight"]), 3),
               "err": round(float(e["err"]), 3)}
        for f in COUNT_FIELDS:
            out[f] = int(e.get(f, 0))
        for f in FLOAT_FIELDS:
            # 6 decimals: display-friendly while keeping the summed
            # rounding drift far below conservation_delta's tolerance
            out[f] = round(float(e.get(f, 0.0)), 6)
        if latencies:
            for key, src in (("ttft_ms", "_ttft"), ("itl_ms", "_itl")):
                s = _summary(e.get(src) or ())
                if s is not None:
                    out[key] = s
        return out

    def snapshot(self) -> dict:
        """The JSON-able top-K table + other bucket + totals.  Also
        publishes the bounded aggregate gauges (`tenant.tracked`,
        `tenant.other_tokens`) — the per-tenant table itself NEVER
        enters the registry."""
        m = _metrics_module()
        with self._lock:
            tenants = {
                t: self._entry_out(e)
                for t, e in sorted(self._tenants.items(),
                                   key=lambda kv: -kv[1]["weight"])}
            other = self._entry_out(self._other, latencies=False)
            other.pop("err", None)
            other["folds"] = self._other_folds
            totals = self._entry_out(self._totals, latencies=False)
            for drop in ("weight", "err"):
                totals.pop(drop, None)
            snap = {"schema": SCHEMA_VERSION, "k": self.k,
                    "tracked": len(self._tenants),
                    "distinct_seen": self._distinct_seen,
                    "tenants": tenants, "other": other,
                    "totals": totals}
            other_tokens = (other["decode_tokens"]
                            + other["prefill_tokens"])
            if m is not None and m.enabled():
                # read back engine.tokens INSIDE the lock: decode incs
                # hold this lock while counting, so this value is
                # exactly consistent with totals.decode_tokens (see
                # module docstring)
                snap["metrics_engine_tokens"] = int(
                    m.snapshot()["counters"].get("engine.tokens", 0))
        if m is not None and m.enabled():
            m.set_gauge("tenant.tracked", snap["tracked"])
            m.set_gauge("tenant.other_tokens", other_tokens)
        return snap

    def conservation(self) -> dict:
        """Per-field invariant deltas: totals − (Σ tracked + other).
        All-zero == the books balance (the chaos gate's assertion)."""
        return conservation_delta(self.snapshot())


# --------------------------- pure helpers ---------------------------
#
# Snapshot-dict functions (no TenantLedger needed): telemetry_agg
# file-loads this module and merges per-replica snapshots with these.

def conservation_delta(snap) -> dict:
    """{field: totals − (Σ tenants + other)} over a snapshot dict.
    Float fields compare within 1e-3 (snapshot values are rounded to
    6 decimals, so honest books drift by ≤ parts·5e-7); a non-empty
    value at any key means the invariant broke."""
    parts = list((snap.get("tenants") or {}).values())
    parts.append(snap.get("other") or {})
    totals = snap.get("totals") or {}
    out = {}
    acc_req: dict = {}
    for p in parts:
        for s, n in (p.get("requests") or {}).items():
            acc_req[s] = acc_req.get(s, 0) + int(n)
    for s in STATUSES:
        d = int((totals.get("requests") or {}).get(s, 0)) \
            - acc_req.get(s, 0)
        if d:
            out[f"requests.{s}"] = d
    for f in COUNT_FIELDS:
        d = int(totals.get(f, 0)) - sum(int(p.get(f, 0)) for p in parts)
        if d:
            out[f] = d
    for f in FLOAT_FIELDS:
        d = float(totals.get(f, 0.0)) - sum(float(p.get(f, 0.0))
                                            for p in parts)
        if abs(d) > 1e-3:
            out[f] = round(d, 6)
    return out


def merge_snapshots(snaps, k=None) -> dict:
    """Merge N ledger snapshots into one fleet-wide snapshot dict.

    Space-Saving merge: matched keys SUM (counts, weight, err);
    when the union exceeds K the smallest-weight entries are evicted —
    their exact counts fold into `~other` (never dropped), exactly as
    a live eviction would.  Per-tenant latency summaries do not merge
    (reservoir percentiles are not additive) and are omitted; the
    per-replica snapshots keep them."""
    snaps = [s for s in snaps if isinstance(s, dict)]
    if k is None:
        k = max([int(s.get("k", DEFAULT_TOPK)) for s in snaps]
                or [DEFAULT_TOPK])
    merged: dict = {}
    other = _new_entry()
    other = {kk: v for kk, v in other.items()
             if not kk.startswith("_")}
    folds = 0
    totals = {f: 0 for f in COUNT_FIELDS}
    totals.update({f: 0.0 for f in FLOAT_FIELDS})
    totals["requests"] = dict.fromkeys(STATUSES, 0)
    distinct = 0
    engine_tokens = 0
    have_engine_tokens = False
    for s in snaps:
        distinct += int(s.get("distinct_seen", 0))
        if "metrics_engine_tokens" in s:
            engine_tokens += int(s["metrics_engine_tokens"])
            have_engine_tokens = True
        for t, e in (s.get("tenants") or {}).items():
            m = merged.setdefault(t, dict(
                {f: 0 for f in COUNT_FIELDS},
                **{f: 0.0 for f in FLOAT_FIELDS},
                requests={}, weight=0.0, err=0.0))
            _fold(m, {"requests": e.get("requests") or {},
                      **{f: e.get(f, 0) for f in COUNT_FIELDS},
                      **{f: e.get(f, 0.0) for f in FLOAT_FIELDS}})
            m["weight"] += float(e.get("weight", 0.0))
            m["err"] += float(e.get("err", 0.0))
        o = s.get("other")
        if o:
            _fold(other, {"requests": o.get("requests") or {},
                          **{f: o.get(f, 0) for f in COUNT_FIELDS},
                          **{f: o.get(f, 0.0) for f in FLOAT_FIELDS}})
            folds += int(o.get("folds", 0))
        tt = s.get("totals") or {}
        for st, n in (tt.get("requests") or {}).items():
            if st in totals["requests"]:
                totals["requests"][st] += int(n)
        for f in COUNT_FIELDS:
            totals[f] += int(tt.get(f, 0))
        for f in FLOAT_FIELDS:
            totals[f] += float(tt.get(f, 0.0))
    # truncate the union back to K: smallest weights fold into ~other
    # (their counts conserve; the fleet table keeps the honest top-K)
    if len(merged) > k:
        by_weight = sorted(merged.items(), key=lambda kv: kv[1]["weight"])
        for t, e in by_weight[:len(merged) - k]:
            _fold(other, e)
            folds += 1
            del merged[t]
    out_tenants = {}
    for t, e in sorted(merged.items(), key=lambda kv: -kv[1]["weight"]):
        row = {"requests": {st: n for st, n in e["requests"].items()
                            if n},
               "weight": round(e["weight"], 3),
               "err": round(e["err"], 3)}
        for f in COUNT_FIELDS:
            row[f] = int(e[f])
        for f in FLOAT_FIELDS:
            row[f] = round(e[f], 6)
        out_tenants[t] = row
    other_out = {"requests": {st: n for st, n in other["requests"].items()
                              if n}, "folds": folds}
    for f in COUNT_FIELDS:
        other_out[f] = int(other.get(f, 0))
    for f in FLOAT_FIELDS:
        other_out[f] = round(float(other.get(f, 0.0)), 6)
    totals_out = {"requests": totals["requests"]}
    for f in COUNT_FIELDS:
        totals_out[f] = int(totals[f])
    for f in FLOAT_FIELDS:
        totals_out[f] = round(float(totals[f]), 6)
    out = {"schema": SCHEMA_VERSION, "k": k,
           "tracked": len(out_tenants), "distinct_seen": distinct,
           "merged_from": len(snaps),
           "tenants": out_tenants, "other": other_out,
           "totals": totals_out}
    if have_engine_tokens:
        out["metrics_engine_tokens"] = engine_tokens
    return out
