"""Replica lifecycle ledger: spawn-to-first-token phase attribution.

ROADMAP item 5 ("kill the cold start") needs a measurement before the
optimization: today the interval between `ReplicaFleet.add_replica()`
and the replica's first routable token is a black box, so the
autoscaler's predictive signal buys capacity of unknown latency.  This
module is that measurement plane.

Two sides, two clocks:

  * `LifecycleLedger` — lives INSIDE a replica process and stamps the
    phases that process can see on its OWN monotonic clock:

        proc_spawn -> imports -> weight_load -> warmup -> announce
                                        (-> first_token, much later)

    Per-program compile wall time (trace/lower vs compile, fed by
    `xla_cost.instrument`) lands in a bounded sub-ledger keyed by
    program label; compiles overflowing the cap fold into `~other` so
    labels stay bounded.

  * `FleetLifecycle` — lives in the SUPERVISOR process (ReplicaFleet)
    and stamps what only it can see, again on its own monotonic clock:

        spawn (Popen) -> announce (file observed) -> first_probe_up
                      -> first_routable_request

Clock-skew join rule: a duration is only ever computed between two
stamps taken by the SAME process's monotonic clock.  Cross-process
joins carry both wall anchors: the supervisor passes its spawn wall
time to the child via `PADDLE_TPU_SPAWN_WALL`, and the child back-dates
its `proc_spawn` stamp by the wall delta — so the child's `imports`
duration covers fork + interpreter start + package imports without
ever differencing two machines'/processes' monotonic clocks.  The
residual that neither side can attribute (announce-file detection lag,
wall skew) is reported honestly as `other`, clamped at zero.

Published metrics (bounded labels, declared at zero by `attach()`):

    lifecycle.phase_ms{phase=...}    gauge, ms of the just-closed phase
    lifecycle.compile_ms{program}    gauge, per-program + {program=~total}
    lifecycle.spawns                 counter
    lifecycle.double_stamps          counter (strict stamps are LOUD)

The full per-spawn records are served by `GET /debug/lifecycle` on
both serving and router, embedded in `/debug/telemetry` and exporter
dumps, and rolled up across processes by `tools/telemetry_agg.py` via
the pure helpers `join` / `validate_record` / `rollup_records`.

Knobs:
  PADDLE_TPU_LIFECYCLE_COMPILE_CAP  distinct program labels kept   (32)
  PADDLE_TPU_LIFECYCLE_HISTORY      per-fleet spawn records kept  (128)
  PADDLE_TPU_REPLICA_WARMUP         fleet: warm up before announce (1)

stdlib-only and file-loadable standalone (tools/telemetry_agg.py loads
this file without the package; sibling imports are guarded).
"""

from __future__ import annotations

import collections
import os
import threading
import time

__all__ = [
    "PHASES",
    "LifecycleLedger",
    "FleetLifecycle",
    "get_ledger",
    "reset",
    "join",
    "validate_record",
    "rollup_records",
]

# Canonical phase order, spawn to first emitted token.  proc_spawn is
# the anchor (zero-duration); everything after it closes a phase.
PHASES = (
    "proc_spawn",
    "imports",
    "weight_load",
    "warmup",
    "announce",
    "first_probe_up",
    "first_routable_request",
    "first_token",
)

# Phases stamped by the replica process itself, in its own ledger.
REPLICA_PHASES = ("proc_spawn", "imports", "weight_load", "warmup", "announce")

# Phases only the supervisor (fleet monitor / router) can observe.
SUPERVISOR_PHASES = ("announce", "first_probe_up", "first_routable_request")

_ORD = {p: i for i, p in enumerate(PHASES)}

SCHEMA = "lifecycle/v1"


def _metrics_module():
    """The metrics sibling, or None when file-loaded standalone."""
    try:
        from . import metrics  # type: ignore

        return metrics
    except ImportError:
        return None


def _flight_module():
    try:
        from . import flight  # type: ignore

        return flight
    except ImportError:
        return None


def compile_cap() -> int:
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_LIFECYCLE_COMPILE_CAP", "32")))
    except ValueError:
        return 32


def history_cap() -> int:
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_LIFECYCLE_HISTORY", "128")))
    except ValueError:
        return 128


class LifecycleLedger:
    """Per-process phase ledger.  One per replica process.

    `stamp()` is STRICT: stamping a phase twice keeps the first stamp,
    increments `lifecycle.double_stamps`, and drops a flight event —
    a silent re-stamp would quietly rewrite history.  Hot paths that
    legitimately race (first_token from concurrent requests) use
    `stamp_once()`, which is quiet first-wins.
    """

    def __init__(self, clock=None, wall=None):
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._stamps = {}  # phase -> (mono, wall)
        self._compiles = collections.OrderedDict()  # label -> dict
        self._double_stamps = 0
        self._begun = False

    # -- stamping -----------------------------------------------------

    def begin(self, spawn_wall=None):
        """Reset and stamp `proc_spawn`.

        `spawn_wall` is the supervisor's wall clock at Popen time
        (PADDLE_TPU_SPAWN_WALL).  When sane (0 <= delta < 1h) the
        proc_spawn stamp is back-dated by the wall delta so the
        `imports` phase covers fork + interpreter + package imports.
        """
        now_m, now_w = self._clock(), self._wall()
        anchor_m, anchor_w = now_m, now_w
        if spawn_wall is not None:
            try:
                delta = now_w - float(spawn_wall)
            except (TypeError, ValueError):
                delta = -1.0
            if 0.0 <= delta < 3600.0:
                anchor_m, anchor_w = now_m - delta, float(spawn_wall)
        with self._lock:
            self._stamps = {"proc_spawn": (anchor_m, anchor_w)}
            self._compiles = collections.OrderedDict()
            self._double_stamps = 0
            self._begun = True
        m = _metrics_module()
        if m is not None:
            m.inc("lifecycle.spawns")
        return anchor_w

    def _put(self, phase, strict):
        if phase not in _ORD:
            raise ValueError(f"unknown lifecycle phase: {phase!r}")
        now_m, now_w = self._clock(), self._wall()
        with self._lock:
            if not self._begun:
                # Stamping before begin(): anchor implicitly so the
                # ledger is never in an unusable state.
                self._stamps.setdefault("proc_spawn", (now_m, now_w))
                self._begun = True
            if phase in self._stamps:
                if strict:
                    self._double_stamps += 1
                    dup = True
                else:
                    return None
            else:
                dup = False
                self._stamps[phase] = (now_m, now_w)
                prev = self._prev_mono_locked(phase, now_m)
        if dup:
            m = _metrics_module()
            if m is not None:
                m.inc("lifecycle.double_stamps")
            f = _flight_module()
            if f is not None:
                try:
                    f.get_recorder().record("lifecycle.double_stamp", phase=phase)
                except Exception:  # pt-lint: ok[PT005]
                    pass           # (the double_stamps counter above IS
                    # the signal; a broken flight ring must not turn a
                    # loud-but-harmless re-stamp into a crash)
            return None
        m = _metrics_module()
        if m is not None:
            m.set_gauge("lifecycle.phase_ms", (now_m - prev) * 1e3, phase=phase)
        return now_m

    def _prev_mono_locked(self, phase, default):  # pt-lint: ok[PT102] (_put holds self._lock)
        """Monotonic time of the nearest earlier stamped phase."""
        best = None
        for p, (mono, _w) in self._stamps.items():
            if p != phase and _ORD[p] < _ORD[phase]:
                if best is None or _ORD[p] > best[0]:
                    best = (_ORD[p], mono)
        return best[1] if best is not None else default

    def stamp(self, phase):
        """Strict stamp: double-stamping is loud (counter + flight)."""
        return self._put(phase, strict=True)

    def stamp_once(self, phase):
        """Quiet first-wins stamp for legitimately racy phases."""
        return self._put(phase, strict=False)

    # -- compile sub-ledger -------------------------------------------

    def record_compile(self, program, lower_ms=0.0, compile_ms=0.0):
        """Attribute one trace/lower/compile to a program label.

        Bounded: past `compile_cap()` distinct labels, new programs
        fold into `~other`.  Publishes `lifecycle.compile_ms{program}`
        per label plus a `{program="~total"}` running sum.
        """
        label = str(program)
        with self._lock:
            if label not in self._compiles and len(self._compiles) >= compile_cap():
                label = "~other"
            e = self._compiles.setdefault(
                label, {"count": 0, "lower_ms": 0.0, "compile_ms": 0.0}
            )
            e["count"] += 1
            e["lower_ms"] += float(lower_ms)
            e["compile_ms"] += float(compile_ms)
            per_label = e["lower_ms"] + e["compile_ms"]
            total = sum(c["lower_ms"] + c["compile_ms"] for c in self._compiles.values())
        m = _metrics_module()
        if m is not None:
            m.set_gauge("lifecycle.compile_ms", per_label, program=label)
            m.set_gauge("lifecycle.compile_ms", total, program="~total")

    # -- snapshot -----------------------------------------------------

    def record(self) -> dict:
        """Serializable snapshot of this process's lifecycle."""
        with self._lock:
            stamps = dict(self._stamps)
            compiles = {k: dict(v) for k, v in self._compiles.items()}
            double = self._double_stamps
        anchor = stamps.get("proc_spawn")
        phases = {}
        for p in PHASES:
            if p in stamps:
                mono, wall = stamps[p]
                phases[p] = {
                    "mono_ms": (mono - anchor[0]) * 1e3 if anchor else 0.0,
                    "wall": wall,
                }
        durations = {}
        prev = None
        for p in PHASES:
            if p not in phases:
                continue
            if prev is not None:
                durations[p] = phases[p]["mono_ms"] - phases[prev]["mono_ms"]
            prev = p
        total = phases[prev]["mono_ms"] if prev is not None else 0.0
        return {
            "schema": SCHEMA,
            "pid": os.getpid(),
            "spawn_wall": anchor[1] if anchor else None,
            "phases": phases,
            "durations_ms": durations,
            "total_ms": total,
            "compiles": compiles,
            "compile_total_ms": sum(
                c["lower_ms"] + c["compile_ms"] for c in compiles.values()
            ),
            "double_stamps": double,
        }


class FleetLifecycle:
    """Supervisor-side spawn records, joined with replica ledgers.

    One per ReplicaFleet.  `spawn(rid)` opens a record (archiving any
    prior spawn of the same rid); the monitor/router stamp the phases
    only they can see; the router attaches the replica's own ledger
    record at first-probe-up so the joined record survives the replica
    being scaled back down.  Memory is bounded: at most
    `history_cap()` records total (active + archived), oldest evicted.
    """

    def __init__(self, clock=None, wall=None):
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._records = collections.OrderedDict()  # rid -> record
        self._archive = collections.deque(maxlen=history_cap())
        self._spawn_samples = collections.deque(maxlen=64)
        self._spawns = 0

    def spawn(self, rid, rank=None) -> float:
        """Open a spawn record; returns the wall anchor to pass to the
        child via PADDLE_TPU_SPAWN_WALL."""
        now_m, now_w = self._clock(), self._wall()
        with self._lock:
            old = self._records.pop(rid, None)
            if old is not None:
                self._archive.append(old)
            self._records[rid] = {
                "rid": rid,
                "rank": rank,
                "spawn_wall": now_w,
                "spawn_mono": now_m,
                "stamps": {},  # phase -> {"mono_ms", "wall"}
                "replica": None,
            }
            while len(self._records) > history_cap():
                self._records.popitem(last=False)
            self._spawns += 1
        m = _metrics_module()
        if m is not None:
            m.inc("lifecycle.spawns")
        return now_w

    def stamp(self, rid, phase) -> bool:
        """First-wins supervisor stamp; returns True if it landed."""
        now_m, now_w = self._clock(), self._wall()
        with self._lock:
            rec = self._records.get(rid)
            if rec is None or phase in rec["stamps"]:
                return False
            ms = (now_m - rec["spawn_mono"]) * 1e3
            rec["stamps"][phase] = {"mono_ms": ms, "wall": now_w}
            if phase == "first_probe_up":
                self._spawn_samples.append(ms)
        m = _metrics_module()
        if m is not None:
            m.set_gauge("lifecycle.phase_ms", ms, phase=phase)
        return True

    def attach_replica_record(self, rid, record) -> bool:
        """Durably attach the replica's own ledger record."""
        if not isinstance(record, dict):
            return False
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return False
            rec["replica"] = record
        return True

    def observed_spawn_ms(self):
        """Median observed spawn -> first_probe_up over recent spawns,
        or None before any spawn completed."""
        with self._lock:
            samples = sorted(self._spawn_samples)
        if not samples:
            return None
        return samples[len(samples) // 2]

    def records(self) -> list:
        """Joined records (active + archived), oldest first."""
        with self._lock:
            raw = list(self._archive) + list(self._records.values())
        return [join(r, r.get("replica")) for r in raw]

    def fleet_view(self) -> dict:
        recs = self.records()
        with self._lock:
            spawns = self._spawns
        return {
            "schema": SCHEMA,
            "spawns": spawns,
            "observed_spawn_ms": self.observed_spawn_ms(),
            "records": recs,
            "rollup": rollup_records(recs),
        }


# -- pure helpers (usable file-loaded, no package required) -----------


def join(sup_record, replica_record) -> dict:
    """Join a supervisor spawn record with the replica's own ledger.

    Durations never cross clocks: replica phases come from the replica
    record (whose proc_spawn anchor is already wall-joined), supervisor
    phases from supervisor stamps.  The unattributable residual is
    `other` (>= 0).
    """
    sup = sup_record or {}
    stamps = sup.get("stamps", {})
    out = {
        "schema": SCHEMA,
        "rid": sup.get("rid"),
        "rank": sup.get("rank"),
        "spawn_wall": sup.get("spawn_wall"),
        "supervisor_ms": {p: s["mono_ms"] for p, s in stamps.items()},
        "replica": replica_record,
        "phases_ms": {},
    }
    phases = dict(out["phases_ms"])
    rep = replica_record if isinstance(replica_record, dict) else None
    rep_durations = (rep or {}).get("durations_ms", {})
    for p in ("imports", "weight_load", "warmup", "announce"):
        if rep is not None:
            phases[p] = float(rep_durations.get(p, 0.0))
    if rep is not None:
        phases["compile"] = float(rep.get("compile_total_ms", 0.0))
    ann = stamps.get("announce", {}).get("mono_ms")
    fpu = stamps.get("first_probe_up", {}).get("mono_ms")
    if ann is not None and fpu is not None:
        phases["probe"] = fpu - ann
    if fpu is not None:
        out["total_ms"] = fpu
        if rep is not None:
            rep_span = (rep.get("phases", {}).get("announce") or {}).get("mono_ms")
            if rep_span is not None and "probe" in phases:
                phases["other"] = max(0.0, fpu - rep_span - phases["probe"])
    out["phases_ms"] = phases
    return out


def validate_record(joined) -> list:
    """Problems with one joined spawn record; [] means complete and
    monotone.  `compile` is an attribution overlay on `warmup`, not a
    timeline phase, so it is exempt from the >= 0 phase checks only in
    the sense that it must still be >= 0 like everything else."""
    problems = []
    if not isinstance(joined, dict):
        return ["not a dict"]
    sup_ms = joined.get("supervisor_ms", {})
    for p in ("announce", "first_probe_up"):
        if p not in sup_ms:
            problems.append(f"supervisor stamp missing: {p}")
    order = [p for p in PHASES if p in sup_ms]
    for a, b in zip(order, order[1:]):
        if sup_ms[b] < sup_ms[a]:
            problems.append(f"supervisor stamps not monotone: {a} -> {b}")
    rep = joined.get("replica")
    if not isinstance(rep, dict):
        problems.append("replica record missing")
    else:
        rphases = rep.get("phases", {})
        for p in REPLICA_PHASES:
            if p not in rphases:
                problems.append(f"replica phase missing: {p}")
        seq = [p for p in PHASES if p in rphases]
        for a, b in zip(seq, seq[1:]):
            if rphases[b].get("mono_ms", 0.0) < rphases[a].get("mono_ms", 0.0):
                problems.append(f"replica phases not monotone: {a} -> {b}")
        for p, d in rep.get("durations_ms", {}).items():
            if d < 0:
                problems.append(f"negative duration: {p} = {d:.3f}ms")
    for p, d in joined.get("phases_ms", {}).items():
        if d < 0:
            problems.append(f"negative joined phase: {p} = {d:.3f}ms")
    return problems


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def rollup_records(joined_records) -> dict:
    """Percentiles per joined phase across spawns (p50/p95/max)."""
    by_phase = {}
    totals = []
    for r in joined_records or []:
        if not isinstance(r, dict):
            continue
        for p, d in r.get("phases_ms", {}).items():
            by_phase.setdefault(p, []).append(float(d))
        if "total_ms" in r:
            totals.append(float(r["total_ms"]))
    out = {"count": len(joined_records or []), "phases": {}}
    for p, vals in sorted(by_phase.items()):
        sv = sorted(vals)
        out["phases"][p] = {
            "count": len(sv),
            "p50": _pct(sv, 0.50),
            "p95": _pct(sv, 0.95),
            "max": sv[-1],
        }
    if totals:
        sv = sorted(totals)
        out["total_ms"] = {
            "count": len(sv),
            "p50": _pct(sv, 0.50),
            "p95": _pct(sv, 0.95),
            "max": sv[-1],
        }
    return out


# -- module default ledger (the replica process's one ledger) ---------

_LEDGER = LifecycleLedger()
_LEDGER_LOCK = threading.Lock()


def get_ledger() -> LifecycleLedger:
    with _LEDGER_LOCK:
        return _LEDGER


def reset() -> None:
    """Replace the process ledger (tests)."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = LifecycleLedger()
