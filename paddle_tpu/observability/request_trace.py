"""Request-level tracing: per-request identity + cross-hop propagation.

The serving plane (ISSUE 7) needs every request to be ONE story across
processes: the client stamps a request id and a traceparent, the server
extracts them, and every span/metric/log either side emits carries the
same identity — so the merged fleet timeline (`tools/telemetry_agg.py`)
shows one request's queue/admission/predict/serialize phases on both
processes' tracks, and a 500 in the server log joins the client attempt
that saw it.

Pieces:
  * `RequestContext` — request id (the operator-facing correlation key,
    echoed as `X-Request-Id`) + W3C-traceparent-style trace/span ids
    and a hop counter.  `child()` derives the next hop (new span id,
    parent recorded) — what a router or a server calling a downstream
    model does before re-injecting headers.
  * contextvar plumbing — `activate(ctx)` scopes a context to the
    current task/thread; `current()` reads it anywhere below (the
    admission controller tags its queue spans without serving passing
    the context through every call).
  * header codec — `to_headers()` / `from_headers()` speak
    `X-Request-Id` plus `traceparent` (`00-<trace>-<span>-01`), so any
    W3C-compatible edge in front of the fleet keeps the chain intact.
  * `request_phase(...)` — the per-phase measurement idiom: a span on
    the `SpanTracer` (args carry the request identity) AND a
    `serving.phase_ms{phase=...,endpoint=...}` histogram observation on
    the shared registry.

stdlib-only (contextvars, uuid) and import-cycle-free like the rest of
`observability/`; the metrics/trace integration is guarded so the
module also works file-loaded standalone.
"""
from __future__ import annotations

import contextlib
import contextvars
import re
import time
import uuid

__all__ = [
    "RequestContext", "new_context", "current", "activate",
    "continue_from_headers", "request_phase", "HEADER_REQUEST_ID",
    "HEADER_TRACEPARENT", "HEADER_TENANT_ID", "HEADER_PRIORITY_CLASS",
    "HEADER_DEADLINE_MS",
]

HEADER_REQUEST_ID = "X-Request-Id"
HEADER_TRACEPARENT = "traceparent"
# tenant identity (ISSUE 16): who to BILL, carried hop-to-hop next to
# who to TRACE — the router's shed for a tenant and the replica's
# decode for the same tenant land in one ledger row
HEADER_TENANT_ID = "X-Tenant-Id"
# QoS identity (ISSUE 18): what was PROMISED, carried hop-to-hop next
# to who to bill — the edge's shed ordering, the scheduler's
# preemption ladder, and the per-class SLO rows all read the same
# class the client stamped (or the tenant→class map resolved)
HEADER_PRIORITY_CLASS = "X-Priority-Class"
HEADER_DEADLINE_MS = "X-Deadline-Ms"

# 00-<32 hex trace id>-<16 hex span id>-<2 hex flags>
_TRACEPARENT = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
# request ids are echoed into headers and filenames: keep them tame
_REQUEST_ID = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")
# tenant ids are ledger keys and debug-table rows: same discipline
# (mirrors tenant_ledger._TENANT_ID — this module stays standalone)
_TENANT_ID = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")
# priority classes are metric labels: closed set, validate-or-drop
# (mirrors inference.qos.CLASSES — this module stays standalone)
_PRIORITY_CLASSES = frozenset(("paid", "free", "batch"))
# deadlines are milliseconds-from-now; clamp keeps a hostile header
# from minting a year-long admission estimate window
_DEADLINE_MAX_MS = 3_600_000

_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_request", default=None)


def _norm_class(value):
    """Validate-or-drop for `X-Priority-Class`: a known class name or
    None.  A garbage class must not mint a garbage metric label."""
    if value is None:
        return None
    v = str(value).strip().lower()
    return v if v in _PRIORITY_CLASSES else None


def _norm_deadline_ms(value):
    """Validate-or-drop for `X-Deadline-Ms`: a positive integer number
    of milliseconds (clamped), or None."""
    if value is None:
        return None
    try:
        ms = int(str(value).strip())
    except (TypeError, ValueError):
        return None
    if ms <= 0:
        return None
    return min(ms, _DEADLINE_MAX_MS)


def _obs_modules():
    """(metrics, trace) from the observability package, or Nones when
    file-loaded standalone."""
    try:
        from . import metrics, trace  # type: ignore

        return metrics, trace
    except ImportError:
        return None, None


class RequestContext:
    """One request's identity at one hop.  Immutable by convention —
    `child()` derives the next hop instead of mutating this one."""

    __slots__ = ("request_id", "trace_id", "span_id", "parent_id",
                 "hop", "tenant_id", "priority_class", "deadline_ms")

    def __init__(self, request_id=None, trace_id=None, span_id=None,
                 parent_id=None, hop=0, tenant_id=None,
                 priority_class=None, deadline_ms=None):
        self.request_id = str(request_id) if request_id \
            else uuid.uuid4().hex[:16]
        self.trace_id = str(trace_id) if trace_id else uuid.uuid4().hex
        self.span_id = str(span_id) if span_id else uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.hop = int(hop)
        # billing identity (ISSUE 16): None means "not established at
        # this hop yet" — the serving edge resolves a fallback (prefix
        # fingerprint, else anon) and every hop below inherits it
        tid = str(tenant_id) if tenant_id is not None else None
        self.tenant_id = tid if tid and _TENANT_ID.match(tid) else None
        # QoS identity (ISSUE 18): None means "not resolved yet" — the
        # first edge resolves tenant→class (qos.resolve_class) and
        # every hop below inherits the resolved class
        self.priority_class = _norm_class(priority_class)
        self.deadline_ms = _norm_deadline_ms(deadline_ms)

    def child(self) -> "RequestContext":
        """The next hop: same request/trace/tenant/QoS identity, fresh
        span id, this hop's span recorded as the parent."""
        return RequestContext(request_id=self.request_id,
                              trace_id=self.trace_id,
                              parent_id=self.span_id, hop=self.hop + 1,
                              tenant_id=self.tenant_id,
                              priority_class=self.priority_class,
                              deadline_ms=self.deadline_ms)

    def to_headers(self) -> dict:
        h = {
            HEADER_REQUEST_ID: self.request_id,
            HEADER_TRACEPARENT: f"00-{self.trace_id}-{self.span_id}-01",
        }
        if self.tenant_id:
            h[HEADER_TENANT_ID] = self.tenant_id
        if self.priority_class:
            h[HEADER_PRIORITY_CLASS] = self.priority_class
        if self.deadline_ms is not None:
            h[HEADER_DEADLINE_MS] = str(self.deadline_ms)
        return h

    def trace_args(self) -> dict:
        """Span args carrying the identity (what every phase span and
        instant attaches so the merged timeline joins on request_id)."""
        args = {"request_id": self.request_id, "trace_id": self.trace_id,
                "span_id": self.span_id, "hop": self.hop}
        if self.parent_id:
            args["parent_span_id"] = self.parent_id
        if self.tenant_id:
            args["tenant_id"] = self.tenant_id
        if self.priority_class:
            args["priority_class"] = self.priority_class
        if self.deadline_ms is not None:
            args["deadline_ms"] = self.deadline_ms
        return args

    def to_dict(self) -> dict:
        return self.trace_args()

    def __repr__(self):
        return (f"RequestContext(request_id={self.request_id!r}, "
                f"hop={self.hop})")

    @classmethod
    def from_headers(cls, headers):
        """Parse an incoming hop from an HTTP header mapping (any object
        with `.get`; `http.server`'s message headers are
        case-insensitive, plain dicts are probed under both casings).
        Returns None when no usable identity is present — a malformed
        traceparent with a valid request id still yields a context (the
        correlation key is the part operators grep for)."""
        def get(name):
            v = headers.get(name)
            if v is None and hasattr(headers, "get"):
                v = headers.get(name.lower()) or headers.get(name.title())
            return v

        rid = get(HEADER_REQUEST_ID)
        if rid is not None and not _REQUEST_ID.match(str(rid)):
            rid = None  # hostile/garbage id: mint our own
        tid = get(HEADER_TENANT_ID)
        if tid is not None and not _TENANT_ID.match(str(tid)):
            tid = None  # hostile/garbage tenant: treat as unset — the
            # edge's fallback derivation owns it from here (a garbage
            # header must not mint a garbage ledger key)
        # QoS headers: validate-or-drop like every identity header (a
        # garbage class/deadline degrades to "unset", never to a 4xx
        # and never to a garbage label)
        pcls = _norm_class(get(HEADER_PRIORITY_CLASS))
        dms = _norm_deadline_ms(get(HEADER_DEADLINE_MS))
        tp = get(HEADER_TRACEPARENT)
        m = _TRACEPARENT.match(str(tp).strip().lower()) if tp else None
        if rid is None and m is None and tid is None and pcls is None:
            return None
        if m is not None:
            # the sender's span becomes our parent; we are a new hop
            return cls(request_id=rid, trace_id=m.group(1),
                       parent_id=m.group(2), hop=1, tenant_id=tid,
                       priority_class=pcls, deadline_ms=dms)
        return cls(request_id=rid, tenant_id=tid, priority_class=pcls,
                   deadline_ms=dms)


def new_context(request_id=None, tenant_id=None, priority_class=None,
                deadline_ms=None) -> RequestContext:
    """Fresh hop-0 context (what a client mints once per request, BEFORE
    its retry loop — all attempts of one request share one id, one
    tenant identity, AND one QoS class/deadline)."""
    return RequestContext(request_id=request_id, tenant_id=tenant_id,
                          priority_class=priority_class,
                          deadline_ms=deadline_ms)


def current():
    """The active RequestContext for this task/thread, or None."""
    return _current.get()


@contextlib.contextmanager
def activate(ctx):
    """Scope `ctx` as the current request for the duration of the
    block (contextvar: safe under the threaded HTTP server AND under
    asyncio if serving ever grows an async front end)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def continue_from_headers(headers) -> RequestContext:
    """Server-side entry: continue the sender's context from HTTP
    headers, or mint a fresh one when the request arrived bare — every
    request has an identity from here on."""
    return RequestContext.from_headers(headers) or new_context()


@contextlib.contextmanager
def request_phase(phase, endpoint="predict", cat="serving", **extra):
    """Measure one request phase: a `serving.<phase>` span on the
    tracer (args = request identity + extras) and a
    `serving.phase_ms{phase=...,endpoint=...}` histogram observation.
    Yields the open Span (or None when tracing is off) so the caller
    can attach results computed inside the phase."""
    metrics, trace = _obs_modules()
    ctx = current()
    args = dict(ctx.trace_args() if ctx is not None else {}, **extra)
    sp = trace.begin(f"serving.{phase}", cat=cat, **args) \
        if trace is not None else None
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        dt_ms = (time.perf_counter() - t0) * 1e3
        if trace is not None:
            trace.end(sp)
        if metrics is not None:
            metrics.observe("serving.phase_ms", dt_ms, phase=str(phase),
                            endpoint=str(endpoint))
