"""Per-process telemetry export: periodic dumps a fleet can aggregate.

Everything the in-process stack collects — metrics snapshot (bucketed
histograms included), new span-tracer events, new flight-ring events,
an optional SLO report — lands as self-describing JSONL lines keyed by
host/pid/rank in a shared directory.  `tools/telemetry_agg.py` merges
the dumps of an N-process fleet (serving replicas, training ranks, the
client side of a hop) into ONE pid-tracked Perfetto timeline plus a
fleet-wide metrics/SLO rollup; `tools/analyze_chip_log.py` validates
the stream with the same discipline as step_stats and trace_event.

Schema (`telemetry_dump/v1`) — one line per dump:
    {"phase": "telemetry_dump", "t": "<ISO8601>", "schema": str,
     "host": str, "pid": int, "rank": int|null, "run_id": str,
     "seq": int, "reason": "periodic"|"final"|"on_demand",
     "wall": float,                      # time.time() at dump
     "trace_wall_epoch": float,          # wall time of the tracer's
                                         # monotonic ts origin — how the
                                         # aggregator aligns processes
     "metrics": {...snapshot...},        # counters/gauges/histograms
     "slo": {...} | null,                # slo.SLOTracker.report()
     "trace_events": [...],              # NEW tracer events since the
                                         # last dump (incremental)
     "flight_events": [...],             # NEW flight events (by seq)
     "timeseries": {"interval_s": f,     # OPTIONAL (ISSUE 15): NEW
                    "frames": [...]},    # sampler frames since the last
                                         # dump (incremental by seq)
     "request_timelines": [...],         # OPTIONAL: recent per-request
                                         # timeline summaries
     "tenants": {...},                   # OPTIONAL (ISSUE 16): the
                                         # process's TenantLedger
                                         # snapshot (full state, not
                                         # incremental — the aggregator
                                         # merges each process's LAST
                                         # dump)
     "lifecycle": {...}}                 # OPTIONAL (ISSUE 17): the
                                         # process's lifecycle record
                                         # (replica) or the fleet view
                                         # (supervisor); full state,
                                         # last dump wins

Incremental on purpose: the tracer buffer holds 64k events — a
per-interval full snapshot would quadratically re-ship history.  Both
cursors (tracer `added()` count, flight `seq`) survive across dumps, so
concatenating one file's lines replays the process's whole story.

`TelemetryExporter.digest()` is the tiny fleet-membership view of the
same data (a few counters, not the streams) — `fleet/elastic.py` rides
it on the heartbeat store so `telemetry_digests()` answers "how is
every live rank doing" without touching the dump directory.

This module keeps its top level stdlib-only AND free of
package-relative imports (the `_obs_modules` guard), so
tools/telemetry_agg.py and tools/analyze_chip_log.py can file-load it.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time

__all__ = [
    "TelemetryExporter", "TELEMETRY_PHASE", "SCHEMA_VERSION",
    "validate_telemetry_stream", "summarize_telemetry_stream",
]

TELEMETRY_PHASE = "telemetry_dump"
SCHEMA_VERSION = "telemetry_dump/v1"
DEFAULT_INTERVAL_S = 30.0

_REQUIRED = {"phase": str, "t": str, "schema": str, "host": str,
             "pid": int, "seq": int, "reason": str,
             "wall": (int, float)}


def _obs_modules():
    """(metrics, trace, flight) siblings, or Nones when file-loaded
    standalone (the validation helpers below need none of them)."""
    try:
        from . import flight, metrics, trace  # type: ignore

        return metrics, trace, flight
    except ImportError:
        return None, None, None


def _timeseries_module():
    """The timeseries sibling, or None when file-loaded standalone."""
    try:
        from . import timeseries  # type: ignore

        return timeseries
    except ImportError:
        return None


def _iso_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S")


class TelemetryExporter:
    """Dump this process's telemetry to `<outdir>/telemetry_<host>_
    <pid>[_r<rank>].jsonl` — once per `interval_s` on a daemon thread
    (`start()`/`stop()`), or explicitly (`dump_once()`).

    `slo` is an optional zero-arg callable returning an SLO report to
    embed (serving passes `server.slo.report`); `extra` a dict merged
    into every line (deployment labels: replica name, zone...)."""

    def __init__(self, outdir=None, interval_s=None, run_id=None,
                 rank=None, host=None, pid=None, slo=None, extra=None,
                 timelines=None, tenants=None, lifecycle=None):
        outdir = outdir or os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
        if not outdir:
            raise ValueError(
                "TelemetryExporter needs an output directory (outdir= "
                "or env PADDLE_TPU_TELEMETRY_DIR)")
        if interval_s is None:
            interval_s = float(os.environ.get(
                "PADDLE_TPU_TELEMETRY_INTERVAL", DEFAULT_INTERVAL_S))
        self.outdir = str(outdir)
        self.interval_s = max(0.05, float(interval_s))
        self.host = str(host) if host else socket.gethostname()
        self.pid = int(pid) if pid is not None else os.getpid()
        if rank is None:
            rank = os.environ.get("PADDLE_TRAINER_ID")
        self.rank = None if rank is None else int(rank)
        self.run_id = str(run_id) if run_id else f"proc_{self.pid}"
        self.slo = slo
        # optional zero-arg callable returning recent RequestTimeline
        # summaries (ISSUE 15): a replica's exporter embeds the engine's
        # per-request latency story next to its metrics
        self.timelines = timelines
        # optional zero-arg callable returning a TenantLedger snapshot
        # (ISSUE 16): each dump carries the process's CURRENT tenant
        # book; telemetry_agg merges the fleet's last dumps
        self.tenants = tenants
        # optional zero-arg callable returning the process's lifecycle
        # record (ISSUE 17): a replica passes its LifecycleLedger's
        # record(); the supervisor passes FleetLifecycle.fleet_view()
        self.lifecycle = lifecycle
        self.extra = dict(extra or {})
        name = f"telemetry_{self.host}_{self.pid}"
        if self.rank is not None:
            name += f"_r{self.rank}"
        self.path = os.path.join(self.outdir, name + ".jsonl")
        self._lock = threading.Lock()
        self._seq = 0
        self._trace_seen = 0
        self._flight_seen = 0
        self._ts_seen = 0
        self._stop = threading.Event()
        self._thread = None
        self._io_lock = threading.Lock()  # serializes file appends
        # only — never held while reading/advancing telemetry state

    # --- dumping -------------------------------------------------------------
    def dump_once(self, reason="on_demand") -> str:
        """Append one dump line; returns the file path.  Thread-safe and
        incremental (only events new since the previous dump ship)."""
        metrics, trace, flight = _obs_modules()
        with self._lock:
            self._seq += 1
            line = {"phase": TELEMETRY_PHASE, "t": _iso_now(),
                    "schema": SCHEMA_VERSION, "host": self.host,
                    "pid": self.pid, "rank": self.rank,
                    "run_id": self.run_id, "seq": self._seq,
                    "reason": str(reason), "wall": time.time()}
            line.update(self.extra)
            # SLO report FIRST: report() publishes the slo.* gauges,
            # so the metrics snapshot below carries the current burn
            # rate instead of the previous interval's
            if self.slo is not None:
                try:
                    line["slo"] = self.slo()
                except Exception as e:
                    # a broken SLO callback must not sink the dump —
                    # but it must be VISIBLE in the stream it broke
                    line["slo_error"] = f"{type(e).__name__}: {e}"
            if metrics is not None:
                line["metrics"] = metrics.snapshot()
            if trace is not None:
                tracer = trace.get_tracer()
                evts = tracer.events()
                added = tracer.added()
                fresh = added - self._trace_seen
                self._trace_seen = added
                line["trace_wall_epoch"] = tracer.wall_epoch
                line["trace_events"] = evts[max(
                    0, len(evts) - max(0, fresh)):] if fresh > 0 else []
            if flight is not None:
                fevts = [e for e in flight.events()
                         if e.get("seq", 0) > self._flight_seen]
                if fevts:
                    self._flight_seen = max(e.get("seq", 0)
                                            for e in fevts)
                line["flight_events"] = fevts
            # the time dimension (ISSUE 15): frames the process-default
            # sampler collected since the last dump — incremental like
            # the trace/flight cursors, so concatenating one file's
            # lines replays the process's whole retained series
            tsmod = _timeseries_module()
            if tsmod is not None:
                sampler = tsmod.get_default_sampler()
                if sampler is not None:
                    frames = sampler.frames_since(self._ts_seen)
                    if frames:
                        self._ts_seen = frames[-1]["seq"]
                    line["timeseries"] = {
                        "interval_s": sampler.interval_s,
                        "frames": frames}
            if self.timelines is not None:
                try:
                    line["request_timelines"] = self.timelines()
                except Exception as e:
                    # same contract as the slo callback: a broken
                    # provider never sinks the dump, but stays VISIBLE
                    line["request_timelines_error"] = \
                        f"{type(e).__name__}: {e}"
            if self.tenants is not None:
                try:
                    line["tenants"] = self.tenants()
                except Exception as e:
                    line["tenants_error"] = f"{type(e).__name__}: {e}"
            if self.lifecycle is not None:
                try:
                    line["lifecycle"] = self.lifecycle()
                except Exception as e:
                    line["lifecycle_error"] = f"{type(e).__name__}: {e}"
        # the disk append runs OUTSIDE _lock: digest() rides the fleet
        # heartbeat and must never wait behind file IO.  _io_lock
        # serializes appends so two concurrent dumps cannot interleave
        # partial lines (the seq/cursor partition above is already
        # consistent — _lock owns it).
        os.makedirs(self.outdir, exist_ok=True)
        with self._io_lock:
            # pt-lint: ok[PT501] (dedicated IO lock: held only across this append, no state read waits on it)
            with open(self.path, "a") as f:
                f.write(json.dumps(line, default=str) + "\n")
        return self.path

    def digest(self) -> dict:
        """The heartbeat-sized view: identity + a handful of rollup
        numbers (requests by status, sheds, goodput gauge when set).
        Small by contract — it rides the fleet store on every beat."""
        metrics, _trace, _flight = _obs_modules()
        with self._lock:
            seq = self._seq
        out = {"host": self.host, "pid": self.pid, "rank": self.rank,
               "run_id": self.run_id, "seq": seq,
               "wall": time.time()}
        if metrics is not None:
            snap = metrics.snapshot()
            counters = snap.get("counters", {})
            out["requests"] = sum(
                v for k, v in counters.items()
                if k.startswith("serving.requests"))
            out["shed"] = sum(
                v for k, v in counters.items()
                if k.startswith("resilience.shed_requests"))
            gauges = snap.get("gauges", {})
            for key in ("goodput.productive_frac", "serving.inflight",
                        "slo.burn_rate{endpoint=predict}"):
                if key in gauges:
                    out[key.split("{")[0].replace(".", "_")] = gauges[key]
        return out

    # --- lifecycle -----------------------------------------------------------
    def start(self):
        """Begin periodic dumps (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle-tpu-telemetry-export")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.dump_once(reason="periodic")
            except Exception:
                metrics, _t, _f = _obs_modules()
                if metrics is not None:
                    # a full disk / unmounted share: count it — the
                    # aggregator's gap and this counter are the evidence
                    metrics.inc("telemetry.export_errors")

    def stop(self, final_dump=True):
        """Stop the periodic thread; by default write one last dump so
        the stream ends with the process's final state."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)
        self._thread = None
        if final_dump:
            try:
                self.dump_once(reason="final")
            except OSError:
                pass  # teardown path: the disk may already be gone

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


# ----------------------- stream validation -----------------------
#
# Pure functions over parsed JSONL entries, mirroring
# step_stats.validate_stream / trace.validate_trace_stream:
# tools/analyze_chip_log.py file-loads this module for them.

def validate_telemetry_stream(entries) -> list:
    """Schema errors for telemetry_dump entries in `entries` (other
    phases ignored — chip logs interleave).  Empty list = valid."""
    errors = []
    seqs: dict = {}
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or e.get("phase") != TELEMETRY_PHASE:
            continue
        for key, typ in _REQUIRED.items():
            if key not in e:
                errors.append(f"entry {i}: missing required key {key!r}")
            elif not isinstance(e[key], typ) or isinstance(e[key], bool):
                errors.append(
                    f"entry {i}: key {key!r} has type "
                    f"{type(e[key]).__name__}, expected {typ}")
        if e.get("schema") not in (None, SCHEMA_VERSION):
            errors.append(f"entry {i}: unknown schema {e.get('schema')!r}")
        for key in ("metrics", "slo", "timeseries", "tenants",
                    "lifecycle"):
            if key in e and e[key] is not None \
                    and not isinstance(e[key], dict):
                errors.append(f"entry {i}: key {key!r} not an object")
        for key in ("trace_events", "flight_events",
                    "request_timelines"):
            if key in e and not isinstance(e[key], list):
                errors.append(f"entry {i}: key {key!r} not a list")
        ts = e.get("timeseries")
        if isinstance(ts, dict) and not isinstance(
                ts.get("frames", []), list):
            errors.append(f"entry {i}: timeseries.frames not a list")
        if isinstance(e.get("seq"), int) and isinstance(e.get("pid"), int):
            ident = (e.get("host"), e["pid"], e.get("rank"))
            prev = seqs.get(ident)
            if prev is not None and e["seq"] <= prev:
                errors.append(
                    f"entry {i}: seq {e['seq']} not increasing for "
                    f"{ident} (prev {prev})")
            seqs[ident] = e["seq"]
    return errors


def summarize_telemetry_stream(entries) -> dict:
    """Per-process digest of a telemetry_dump stream: dump counts,
    shipped event counts, last counters-total per process."""
    procs: dict = {}
    for e in entries:
        if not isinstance(e, dict) or e.get("phase") != TELEMETRY_PHASE:
            continue
        ident = f"{e.get('host', '?')}:{e.get('pid', '?')}" + (
            f":r{e['rank']}" if e.get("rank") is not None else "")
        s = procs.setdefault(ident, {
            "dumps": 0, "trace_events": 0, "flight_events": 0})
        s["dumps"] += 1
        s["trace_events"] += len(e.get("trace_events") or ())
        s["flight_events"] += len(e.get("flight_events") or ())
        m = e.get("metrics")
        if isinstance(m, dict):
            counters = m.get("counters", {})
            if isinstance(counters, dict):
                s["counters_total"] = sum(
                    v for v in counters.values()
                    if isinstance(v, (int, float)))
        if isinstance(e.get("slo"), dict):
            s["has_slo"] = True
    return procs
