"""paddle.reader parity (`python/paddle/reader/`): legacy reader-creator
decorators used by `paddle_tpu.dataset`. A *reader creator* is a zero-arg
callable returning an iterable of samples."""
from .decorator import (  # noqa: F401
    ComposeNotAligned, buffered, cache, chain, compose, firstn,
    map_readers, multiprocess_reader, shuffle, xmap_readers,
)

__all__ = []
