"""paddle.reader decorators (reference `python/paddle/reader/decorator.py`):
composable transformations over *reader creators* — zero-arg callables
returning a fresh iterable of samples. The legacy io tier still used by
`paddle.dataset.*`; `paddle_tpu.io.DataLoader` is the modern path.
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue as queue_mod
import random
import threading

__all__ = []


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Materialize `reader`'s samples on the first COMPLETE pass; later
    passes replay from memory (reference decorator.py:45). An abandoned
    partial pass discards its accumulation — a later full pass re-reads
    from scratch rather than replaying duplicated samples."""
    all_data = []
    filled = [False]

    def cached_reader():
        if filled[0]:
            yield from all_data
            return
        data = []
        for item in reader():
            data.append(item)
            yield item
        all_data[:] = data
        filled[0] = True

    return cached_reader


def map_readers(func, *readers):
    """Yield func(*items) over the zipped readers (decorator.py:86)."""

    def reader():
        rs = [r() for r in readers]
        yield from map(func, *rs)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: read `buf_size` samples, shuffle, emit
    (decorator.py:127)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers: all of A's samples, then B's, …
    (decorator.py:172)."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined samples: (a, (b1, b2), c) -> (a, b1,
    b2, c). check_alignment=True (default) raises ComposeNotAligned when
    the readers run out at different lengths (decorator.py:235)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Producer thread fills a bounded queue of up to `size` samples the
    consumer drains — overlaps data reading with compute
    (decorator.py:292). A producer exception is forwarded and re-raised
    in the consumer — a broken stream must not masquerade as a short
    dataset."""

    class _End:
        pass

    def data_reader():
        q = queue_mod.Queue(maxsize=size)

        def produce():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as e:  # noqa: BLE001 — forwarded
                q.put(_MapperError(e))
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, _MapperError):
                raise e.exc
            yield e

    return data_reader


def firstn(reader, n):
    """Only the first n samples (decorator.py:357)."""

    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


class _MapperError:
    """Exception carrier from an xmap worker thread to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` over samples with `process_num` worker THREADS and
    a `buffer_size`-bounded pipeline; order=True preserves input order
    (decorator.py:402 — the reference also uses threads)."""

    end_token = object()

    def xreader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def read_worker():
            # end tokens ALWAYS go out (finally): a reader exception must
            # surface in the consumer, never strand the worker threads
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d) if order else d)
            except BaseException as e:  # noqa: BLE001 — forwarded
                out_q.put(_MapperError(e))
            finally:
                for _ in range(process_num):
                    in_q.put(end_token)

        def handle_worker():
            # the end token ALWAYS goes out (finally): a mapper exception
            # must surface to the consumer, never hang it
            try:
                while True:
                    item = in_q.get()
                    if item is end_token:
                        return
                    if order:
                        i, d = item
                        out_q.put((i, mapper(d)))
                    else:
                        out_q.put(mapper(item))
            except BaseException as e:  # noqa: BLE001 — forwarded
                out_q.put(_MapperError(e))
            finally:
                out_q.put(end_token)

        threading.Thread(target=read_worker, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=handle_worker, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            nxt = 0
            while finished < process_num:
                item = out_q.get()
                if item is end_token:
                    finished += 1
                    continue
                if isinstance(item, _MapperError):
                    raise item.exc
                i, d = item
                pending[i] = d
                while nxt in pending:
                    yield pending.pop(nxt)
                    nxt += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end_token:
                    finished += 1
                    continue
                if isinstance(item, _MapperError):
                    raise item.exc
                yield item

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run each reader in its own PROCESS, merging samples into one
    stream (decorator.py:498). Samples must be picklable."""
    if len(readers) < 1:
        raise ValueError("readers must not be empty")

    def _worker(r, q):
        # a worker exception is forwarded (as a repr — the exception
        # object itself may not pickle) and re-raised in the consumer,
        # never reported as a clean short stream
        try:
            for d in r():
                q.put(d)
        except BaseException as e:  # noqa: BLE001 — forwarded
            q.put(("__reader_error__", f"{type(e).__name__}: {e}"))
        finally:
            q.put(None)

    def merged():
        q = multiprocessing.Queue(queue_size)
        procs = [multiprocessing.Process(target=_worker, args=(r, q),
                                         daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            sample = q.get()
            if sample is None:
                finished += 1
                continue
            if isinstance(sample, tuple) and len(sample) == 2 and \
                    sample[0] == "__reader_error__":
                raise RuntimeError(
                    f"multiprocess_reader worker failed: {sample[1]}")
            yield sample
        for p in procs:
            p.join()

    return merged
