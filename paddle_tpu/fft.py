"""Discrete Fourier transforms (paddle.fft parity:
`/root/reference/python/paddle/fft.py`).

TPU-first: every transform lowers to XLA's FFT HLO via jnp.fft — batched,
fusable, and differentiable under the same vjp tape as every other op.
Norm conventions ("backward"/"ortho"/"forward") match the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"Unexpected norm: {norm!r}")
    return norm


@op("fft")
def fft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.fft(x, n=n, axis=axis, norm=_norm(norm))


@op("ifft")
def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=_norm(norm))


@op("rfft")
def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=_norm(norm))


@op("irfft")
def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_norm(norm))


@op("hfft")
def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=_norm(norm))


@op("ihfft")
def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=_norm(norm))


@op("fft2")
def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=_norm(norm))


@op("ifft2")
def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=_norm(norm))


@op("rfft2")
def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=_norm(norm))


@op("irfft2")
def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=_norm(norm))


def _hfftn_raw(x, s, axes, norm):
    # hfftn = forward fft over the leading axes, then hfft on the last
    # (jnp has no hfftn; matches scipy.fft.hfftn numerically)
    if axes is None:
        axes = tuple(range(x.ndim))
    for i, ax in enumerate(axes[:-1]):
        n_i = None if s is None else s[i]
        x = jnp.fft.fft(x, n=n_i, axis=ax, norm=norm)
    n_last = None if s is None else s[-1]
    return jnp.fft.hfft(x, n=n_last, axis=axes[-1], norm=norm)


def _ihfftn_raw(x, s, axes, norm):
    if axes is None:
        axes = tuple(range(x.ndim))
    n_last = None if s is None else s[-1]
    out = jnp.fft.ihfft(x, n=n_last, axis=axes[-1], norm=norm)
    for i, ax in enumerate(axes[:-1]):
        n_i = None if s is None else s[i]
        out = jnp.fft.ifft(out, n=n_i, axis=ax, norm=norm)
    return out


@op("hfft2")
def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _hfftn_raw(x, s, axes, _norm(norm))


@op("ihfft2")
def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _ihfftn_raw(x, s, axes, _norm(norm))


@op("fftn")
def fftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=_norm(norm))


@op("ifftn")
def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=_norm(norm))


@op("rfftn")
def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=_norm(norm))


@op("irfftn")
def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=_norm(norm))


@op("hfftn")
def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn_raw(x, s, axes, _norm(norm))


@op("ihfftn")
def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn_raw(x, s, axes, _norm(norm))


@op("fftfreq")
def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=d)
    return out.astype(dtype) if dtype is not None else out


@op("rfftfreq")
def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=d)
    return out.astype(dtype) if dtype is not None else out


@op("fftshift")
def fftshift(x, axes=None, name=None):
    return jnp.fft.fftshift(x, axes=axes)


@op("ifftshift")
def ifftshift(x, axes=None, name=None):
    return jnp.fft.ifftshift(x, axes=axes)
