"""Analytic cost model for parallel-plan search.

Role parity: `python/paddle/cost_model/` +
`python/paddle/distributed/auto_parallel/static/cost/` (SURVEY §2.8) — op
compute/communication cost estimates the auto-parallel planner and
auto-tuner prune with.

TPU-first numbers: costs are parameterized by chip specs (default v5p-ish:
459 TFLOP/s bf16, 2.77 TB/s HBM, 100 GB/s/link ICI ring) instead of A100
CUDA latencies; collective models are the standard ring/all-gather forms
over ICI, matching the scaling-book mental model.
"""
from __future__ import annotations



class ChipSpec:
    def __init__(self, flops=459e12, hbm_bw=2.765e12, hbm_gb=95,
                 ici_bw=9e10, dcn_bw=2.5e10):
        self.flops = flops          # peak bf16 FLOP/s
        self.hbm_bw = hbm_bw        # bytes/s
        self.hbm_bytes = hbm_gb * 1e9
        self.ici_bw = ici_bw        # bytes/s per link direction
        self.dcn_bw = dcn_bw


V5P = ChipSpec()


class CostEstimate:
    __slots__ = ("compute_s", "memory_s", "comm_s")

    def __init__(self, compute_s=0.0, memory_s=0.0, comm_s=0.0):
        self.compute_s = compute_s
        self.memory_s = memory_s
        self.comm_s = comm_s

    @property
    def total_s(self):
        # compute and memory overlap on-chip; comm overlaps partially —
        # use max(compute, memory) + comm as the conservative roofline
        return max(self.compute_s, self.memory_s) + self.comm_s

    def __add__(self, o):
        return CostEstimate(self.compute_s + o.compute_s,
                            self.memory_s + o.memory_s,
                            self.comm_s + o.comm_s)

    def __repr__(self):
        return (f"CostEstimate(compute={self.compute_s:.2e}s, "
                f"memory={self.memory_s:.2e}s, comm={self.comm_s:.2e}s)")


def matmul_cost(m, k, n, dtype_bytes=2, chip=V5P):
    flops = 2.0 * m * k * n
    bytes_moved = dtype_bytes * (m * k + k * n + m * n)
    return CostEstimate(flops / chip.flops, bytes_moved / chip.hbm_bw)


def elementwise_cost(numel, dtype_bytes=2, n_operands=2, chip=V5P):
    return CostEstimate(numel / chip.flops,
                        numel * dtype_bytes * (n_operands + 1) / chip.hbm_bw)


def allreduce_cost(bytes_, n, chip=V5P, inter_host=False):
    """Ring allreduce: 2(n-1)/n * bytes over the slowest link."""
    if n <= 1:
        return CostEstimate()
    bw = chip.dcn_bw if inter_host else chip.ici_bw
    return CostEstimate(comm_s=2.0 * (n - 1) / n * bytes_ / bw)


def allgather_cost(bytes_per_shard, n, chip=V5P, inter_host=False):
    if n <= 1:
        return CostEstimate()
    bw = chip.dcn_bw if inter_host else chip.ici_bw
    return CostEstimate(comm_s=(n - 1) * bytes_per_shard / bw)


reduce_scatter_cost = allgather_cost


def alltoall_cost(bytes_total, n, chip=V5P, inter_host=False):
    if n <= 1:
        return CostEstimate()
    bw = chip.dcn_bw if inter_host else chip.ici_bw
    return CostEstimate(comm_s=(n - 1) / n * bytes_total / bw)


def p2p_cost(bytes_, chip=V5P, inter_host=False):
    bw = chip.dcn_bw if inter_host else chip.ici_bw
    return CostEstimate(comm_s=bytes_ / bw)


# --- transformer-block level model (what the auto-tuner prunes with) --------

class TransformerShape:
    def __init__(self, hidden, ffn_hidden, num_heads, seq_len, vocab_size,
                 num_layers, dtype_bytes=2):
        self.h = hidden
        self.f = ffn_hidden
        self.heads = num_heads
        self.s = seq_len
        self.v = vocab_size
        self.L = num_layers
        self.b = dtype_bytes

    def params(self):
        per_layer = (4 * self.h * self.h          # qkv + out
                     + 3 * self.h * self.f)       # swiglu-ish mlp
        return self.L * per_layer + 2 * self.v * self.h

    def flops_per_token(self):
        # 6 * params (fwd+bwd) + attention term
        return 6 * self.params() + 12 * self.L * self.h * self.s


def train_step_cost(shape, global_batch, micro_batch, dp=1, mp=1, pp=1,
                    sharding_stage=0, chip=V5P, n_hosts=1):
    """Roofline step-time estimate for a hybrid plan (auto-tuner metric)."""
    tokens = global_batch * shape.s
    flops = shape.flops_per_token() * tokens
    n_chips = dp * mp * pp
    compute = CostEstimate(compute_s=flops / (chip.flops * n_chips))

    comm = CostEstimate()
    param_bytes = shape.params() * shape.b
    if mp > 1:
        # 4 allreduces per layer per micro-batch (fwd+bwd, attn+mlp)
        act_bytes = micro_batch * shape.s * shape.h * shape.b
        per = allreduce_cost(act_bytes, mp, chip)
        n_micro = max(1, global_batch // (micro_batch * dp))
        comm += CostEstimate(comm_s=4 * shape.L * n_micro * per.comm_s)
    if dp > 1:
        grad_bytes = param_bytes / max(mp, 1) / max(pp, 1)
        if sharding_stage >= 2:
            comm += reduce_scatter_cost(grad_bytes / dp, dp, chip,
                                        inter_host=n_hosts > 1)
            comm += allgather_cost(grad_bytes / dp, dp, chip,
                                   inter_host=n_hosts > 1)
        else:
            comm += allreduce_cost(grad_bytes, dp, chip,
                                   inter_host=n_hosts > 1)
    if pp > 1:
        act_bytes = micro_batch * shape.s * shape.h * shape.b
        n_micro = max(1, global_batch // (micro_batch * dp))
        # 1F1B: (pp-1 + n_micro) pipeline slots, 2 P2P per boundary
        comm += CostEstimate(
            comm_s=2 * (pp - 1 + n_micro) * p2p_cost(act_bytes, chip).comm_s)
    return compute + comm


def comm_bytes_per_step(param_count, local_batch, seq, hidden, num_layers,
                        dp=1, mp=1, sep=1, sharding_stage=0,
                        sequence_parallel=False, context_parallel=False,
                        grad_dtype_bytes=4, param_dtype_bytes=4,
                        act_dtype_bytes=2):
    """Predicted per-device collective payload bytes for ONE optimizer step
    of the compiled hybrid train step (VERDICT r4 Next #6: the analytic
    half of the planner's feedback loop — validated against
    `completion.collective_report`'s compiler ground truth, which reads
    the per-device shapes out of the partitioned HLO).

    Structural terms (per device, matching what GSPMD inserts):
      * dp grad sync     — all-reduce (or reduce-scatter + param
                           all-gather under ZeRO>=1 weight-update
                           sharding) of the mp-local grads
      * ZeRO-3           — extra param all-gathers in fwd+bwd
      * TP (mp)          — 4 activation all-reduces per layer (2 fwd +
                           2 bwd; Megatron); with sequence_parallel the
                           same bytes move as all-gather+reduce-scatter
      * SEP ring         — K/V (and their grads) rotating sep-1 hops per
                           layer via collective-permute

    Returns {"by_kind": {...}, "total": int}. Agreement with the
    measured report within ~3x is expected; the planner re-ranks with
    the measured bytes (Engine.search).
    """
    by = {"all-reduce": 0.0, "reduce-scatter": 0.0, "all-gather": 0.0,
          "collective-permute": 0.0, "all-to-all": 0.0}
    p_local = param_count / max(mp, 1)
    if dp > 1:
        g = p_local * grad_dtype_bytes
        if sharding_stage >= 3:
            # params stay dp-sharded through the update (no post-update
            # gather); fwd + bwd each re-gather them on demand
            by["reduce-scatter"] += g
            by["all-gather"] += 2 * p_local * param_dtype_bytes
        elif sharding_stage == 2:
            by["reduce-scatter"] += g
            by["all-gather"] += p_local * param_dtype_bytes
        elif sharding_stage == 1:
            by["all-reduce"] += g
            by["all-gather"] += p_local * param_dtype_bytes
        else:
            by["all-reduce"] += g
    if mp > 1:
        a = local_batch * seq * hidden * act_dtype_bytes
        if sequence_parallel:
            by["all-gather"] += 2 * num_layers * a
            by["reduce-scatter"] += 2 * num_layers * a
        else:
            by["all-reduce"] += 4 * num_layers * a
    if sep > 1 and context_parallel:
        # ring attention: K+V rotate (sep-1) hops forward; backward
        # re-rotates K/V and accumulates dK/dV around the ring
        kv = local_batch * (seq // sep) * hidden * act_dtype_bytes
        by["collective-permute"] += 5 * num_layers * (sep - 1) * kv
    total = sum(by.values())
    return {"by_kind": {k: int(v) for k, v in by.items() if v},
            "total": int(total)}


def memory_per_chip(shape, micro_batch, dp=1, mp=1, pp=1, sharding_stage=0,
                    recompute=False, optimizer_bytes_per_param=12):
    """Bytes/chip estimate for pruning infeasible plans (weights + grads +
    optimizer state + activations)."""
    p_local = shape.params() / mp / pp
    weights = p_local * shape.b
    grads = p_local * shape.b
    opt = p_local * optimizer_bytes_per_param
    if sharding_stage >= 1:
        opt /= dp
    if sharding_stage >= 2:
        grads /= dp
    if sharding_stage >= 3:
        weights /= dp
    layers_local = max(1, shape.L // pp)
    act_per_layer = micro_batch * shape.s * shape.h * shape.b
    act = act_per_layer * (1 if recompute else layers_local) * \
        (14 if not recompute else 2)  # rough transformer activation factor
    return weights + grads + opt + act
