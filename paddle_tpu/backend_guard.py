"""Backend bootstrap guards.

This environment registers an out-of-tree TPU PJRT plugin ("axon") from
``sitecustomize`` at interpreter start and pins ``JAX_PLATFORMS`` to it.
When the TPU tunnel behind the plugin is down, backend initialization
either raises ``UNAVAILABLE`` or blocks indefinitely — taking down any
script whose first jax call is ``jax.devices()``.

Two defenses live here (used by ``bench.py``, ``__graft_entry__.py`` and
mirrored by ``tests/conftest.py``):

``probe_default_backend(timeout)``
    Initialize the default backend in a *subprocess* with a hard timeout,
    so a hung plugin init cannot hang the caller. Returns
    ``(platform, device_count)`` or ``None``.

``force_cpu_mesh(n_devices)``
    Re-point jax at the host-CPU platform with ``n_devices`` virtual
    devices (the same mesh-emulation trick the reference's tests use for
    multi-device runs without a cluster, cf. SURVEY.md §4 note on
    ``xla_force_host_platform_device_count``), dropping the flaky plugin
    factory first. Safe to call whether or not backends were already
    initialized: initialized backends are cleared so the forced platform
    takes effect.
"""
from __future__ import annotations

import os
import re
import subprocess
import sys

_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print(d[0].platform, len(d))"
)


def enable_persistent_compile_cache(cache_dir: str,
                                    min_compile_secs: float = 1.0) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` (one
    shared helper so the watcher's chip sessions and the driver's
    bench.py read/write the SAME executable cache — on a tunnel that
    yields minutes-long windows, compile reuse across processes is the
    difference between a window producing data and producing nothing).
    Returns True when enabled."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        return True
    except Exception:
        return False


def probe_default_backend(timeout: float = 120.0, retries: int = 2,
                          backoff: float = 0.0):
    """Probe the default jax backend in a subprocess.

    Returns ``(platform: str, n_devices: int)`` on success, ``None`` if
    every attempt fails or times out. A subprocess is the only reliable
    watchdog: a PJRT plugin stuck in native code ignores Python-level
    signals/threads. ``backoff`` seconds of sleep are added between
    attempts (a flapping remote tunnel often recovers within minutes —
    retrying with backoff beats falling to a degraded CPU proxy)."""
    import time as _time

    for attempt in range(max(1, retries)):
        if attempt and backoff:
            _time.sleep(backoff)
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout,
            )
        except (subprocess.TimeoutExpired, OSError):
            continue
        if r.returncode == 0 and r.stdout.strip():
            parts = r.stdout.split()
            if len(parts) >= 2:
                try:
                    return parts[0], int(parts[1])
                except ValueError:
                    pass
    return None


def force_cpu_mesh(n_devices: int = 8):
    """Force the host-CPU platform with ``n_devices`` virtual devices.

    Returns the ``jax`` module, guaranteed to expose at least
    ``n_devices`` CPU devices on the next ``jax.devices()`` call.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

    # Drop the axon PJRT factory before jax touches backends, so even an
    # explicit platform list containing it cannot trigger plugin init.
    try:
        from jax._src import xla_bridge as _xb

        d = getattr(_xb, "_backend_factories", None)
        if isinstance(d, dict):
            d.pop("axon", None)
    except Exception:
        from .observability import metrics as _metrics

        _metrics.inc("backend.guard_swallowed", stage="drop_factory")

    import jax

    # If a backend was already initialized (e.g. entry() compile-checked,
    # or a previous force_cpu_mesh with a different count ran), clear it
    # FIRST: `jax_num_cpu_devices` refuses updates while backends are
    # live, and the old (swallowed) order left the previous device count
    # pinned — a force_cpu_mesh(1) followed by force_cpu_mesh(8) stayed
    # at 1 device (slow-tier ordering bug, round 4).
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            jax.clear_caches()
            _xb._clear_backends()
    except Exception:
        from .observability import metrics as _metrics

        _metrics.inc("backend.guard_swallowed", stage="clear_backends")

    # sitecustomize imported jax before us, so the config snapshot may
    # already hold JAX_PLATFORMS=axon — override at the config level too.
    for key, val in (("jax_platforms", "cpu"),
                     ("jax_num_cpu_devices", n_devices)):
        try:
            jax.config.update(key, val)
        except Exception:
            # expected on older jax (the config key does not exist
            # there) — counted, not silent, so a genuinely broken
            # config update is visible in the metrics snapshot
            from .observability import metrics as _metrics

            _metrics.inc("backend.guard_swallowed", stage="config:" + key)
    return jax
