"""paddle_tpu.quantization: QAT / PTQ workflows.

Role parity: `paddle.quantization` (`python/paddle/quantization/`, SURVEY
§2.6) — QuantConfig with layer/type/name rules, observers (PTQ statistics
collectors), fake quanters (QAT simulated quantization), and the
QAT/PTQ drivers that swap layers for quantized twins.

TPU-first: quantization is *simulated* in bf16/f32 compute (fake-quant with
straight-through gradients) exactly as the reference's QAT does on GPU; the
deployment win comes from exporting the quantized graph (int8 weights +
scales) where XLA lowers to int8 MXU matmuls. The STE round-trip is a
single fused elementwise chain under XLA — no custom kernels needed.
"""
from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = [
    "QuantConfig", "QAT", "PTQ", "quanters", "observers",
    "BaseQuanter", "BaseObserver", "weight_only_quantize",
]


class BaseObserver(Layer):
    """Collects activation statistics during calibration (PTQ)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._stat = None

    def forward(self, x):
        self._observe(x)
        return x

    def _observe(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return 0.0

    def quant_axis(self):
        return -1

    def bit_length(self):
        return self.quant_bits


class AbsmaxObserver(BaseObserver):
    """Per-tensor abs-max (parity: observers.AbsmaxObserver)."""

    def _observe(self, x):
        m = float(np.max(np.abs(np.asarray(x._value))))
        self._stat = m if self._stat is None else max(self._stat, m)

    def scales(self):
        if self._stat is None:
            raise RuntimeError("observer saw no data; run calibration first")
        qmax = 2 ** (self.quant_bits - 1) - 1
        return self._stat / qmax


class EMAObserver(BaseObserver):
    """Exponential-moving-average abs-max."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def _observe(self, x):
        m = float(np.max(np.abs(np.asarray(x._value))))
        if self._stat is None:
            self._stat = m
        else:
            self._stat = self.moving_rate * self._stat \
                + (1 - self.moving_rate) * m

    scales = AbsmaxObserver.scales


class BaseQuanter(Layer):
    pass


def _fake_quant(x, scale, qmax):
    """Simulated quant with straight-through gradient."""

    def f(v, s):
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax) * s
        # STE: identity gradient through the round/clip
        return v + jax.lax.stop_gradient(q - v)

    return apply("fake_quant", f, x, scale)


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT quanter: tracks a moving abs-max scale and fake-quantizes
    (parity: quanters.FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.register_buffer("_scale", Tensor(np.ones((), np.float32)))
        # calibration flag is a buffer so it survives state_dict round-trips
        # (a trained quanter loaded from a checkpoint must keep quantizing)
        self.register_buffer("_calibrated", Tensor(np.zeros((), np.float32)))

    @property
    def _initialized(self):
        return bool(float(self._calibrated._value) > 0)

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1
        if not self.training and not self._initialized:
            # uncalibrated: the default scale 1.0 would round activations
            # to integers; pass through instead (cf. AbsmaxObserver, which
            # raises when asked for scales it never observed)
            return x
        if self.training:
            cur = float(np.max(np.abs(np.asarray(x._value)))) / qmax
            if not self._initialized:
                self._scale._value = jnp.asarray(cur, jnp.float32)
                self._calibrated._value = jnp.asarray(1.0, jnp.float32)
            else:
                r = self.moving_rate
                self._scale._value = (r * self._scale._value
                                      + (1 - r) * cur)
        return _fake_quant(x, Tensor(self._scale._value), qmax)

    def scales(self):
        return float(self._scale._value)

    def bit_length(self):
        return self.quant_bits


class FakeQuanterChannelWiseAbsMax(BaseQuanter):
    """Per-channel weight quanter (axis 0 = output channels)."""

    def __init__(self, quant_bits=8, quant_axis=0):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1
        axes = tuple(i for i in range(x.ndim) if i != self.quant_axis)

        def f(v):
            s = jnp.max(jnp.abs(v), axis=axes, keepdims=True) / qmax
            s = jnp.maximum(s, 1e-9)
            q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax) * s
            return v + jax.lax.stop_gradient(q - v)

        return apply("fake_quant_channelwise", f, x)


class quanters:
    FakeQuanterWithAbsMaxObserver = FakeQuanterWithAbsMaxObserver
    FakeQuanterChannelWiseAbsMax = FakeQuanterChannelWiseAbsMax


class observers:
    AbsmaxObserver = AbsmaxObserver
    EMAObserver = EMAObserver


class _Factory:
    """Wraps a quanter/observer class + kwargs (parity: QuanterFactory)."""

    def __init__(self, cls, **kwargs):
        self.cls = cls
        self.kwargs = kwargs

    def instance(self):
        return self.cls(**self.kwargs)


def quanter_factory(cls, **kwargs):
    return _Factory(cls, **kwargs)


class QuantConfig:
    """Which layers get which activation/weight quanters (parity:
    `python/paddle/quantization/config.py`)."""

    def __init__(self, activation=None, weight=None):
        self._global_act = self._wrap(activation)
        self._global_weight = self._wrap(weight)
        self._layer_cfg = []   # (predicate, act_factory, weight_factory)

    @staticmethod
    def _wrap(q):
        if q is None or isinstance(q, _Factory):
            return q
        if isinstance(q, type):
            return _Factory(q)
        return _Factory(type(q))

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        ids = {id(l) for l in layers}
        self._layer_cfg.append(
            (lambda l: id(l) in ids, self._wrap(activation),
             self._wrap(weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = tuple(layer_type) if isinstance(layer_type, (list, tuple)) \
            else (layer_type,)
        self._layer_cfg.append(
            (lambda l: isinstance(l, types), self._wrap(activation),
             self._wrap(weight)))

    def add_name_config(self, names, activation=None, weight=None):
        nameset = set(names if isinstance(names, (list, tuple)) else [names])
        self._layer_cfg.append(
            (lambda l: getattr(l, "_quant_name", None) in nameset,
             self._wrap(activation), self._wrap(weight)))

    def _config_for(self, layer):
        for pred, act, w in self._layer_cfg:
            if pred(layer):
                return act, w
        return self._global_act, self._global_weight


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + activation."""

    def __init__(self, source, act_quanter, weight_quanter):
        super().__init__()
        self.weight = source.weight
        self.bias = getattr(source, "bias", None)
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from .. import ops

        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        out = ops.matmul(x, w)
        if self.bias is not None:
            out = ops.add(out, self.bias)
        return out


class QuantedConv2D(Layer):
    def __init__(self, source, act_quanter, weight_quanter):
        super().__init__()
        self._source = source
        self.weight = source.weight
        self.bias = getattr(source, "bias", None)
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from ..nn import functional as F

        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        src = self._source
        return F.conv2d(x, w, self.bias, stride=src.stride,
                        padding=src.padding, dilation=src.dilation,
                        groups=src.groups, data_format=src.data_format)


def _make_quanted(config, layer, force_observer=False):
    """Build the quantized twin for a swappable layer, or None. Shared by
    the QAT and PTQ drivers (PTQ coerces activation quanters to
    observers). Bare `nn.quant.Stub`s swap for the configured activation
    quanter/observer (reference stub.py contract)."""
    from ..nn.layers_common import Linear
    from ..nn.layers_conv_pool import Conv2D
    from ..nn.quant import Stub

    if isinstance(layer, Stub):
        if layer._observer is not None:
            # self-configured stub: QAT keeps its quanter; PTQ coerces it
            # to an observer like every other activation quanter (an
            # uncalibrated quanter in eval calibration would silently
            # no-op forever after convert)
            if force_observer and not isinstance(layer._observer,
                                                 BaseObserver):
                return Stub(AbsmaxObserver())
            return None
        act_f, _ = config._config_for(layer)
        if act_f is None:
            return None
        act = act_f.instance()
        if force_observer and not isinstance(act, BaseObserver):
            act = AbsmaxObserver()
        return Stub(act)
    if not isinstance(layer, (Conv2D, Linear)):
        return None
    act_f, w_f = config._config_for(layer)
    if act_f is None and w_f is None:
        return None
    act = act_f.instance() if act_f else None
    if force_observer and act is not None and \
            not isinstance(act, BaseObserver):
        act = AbsmaxObserver()
    w = w_f.instance() if w_f else None
    if isinstance(layer, Conv2D):
        return QuantedConv2D(layer, act, w)
    return QuantedLinear(layer, act, w)


def _swap_layers(model, make_twin):
    """Replace sublayers in-place: make_twin(layer) returns the
    replacement or None (no match -> recurse into the layer)."""
    for name, sub in list(model.named_children()):
        twin = make_twin(sub)
        if twin is not None:
            setattr(model, name, twin)
        else:
            _swap_layers(sub, make_twin)
    return model


class QAT:
    """Quantization-aware training driver (parity: quantization/qat.py)."""

    def __init__(self, config):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)
        return _swap_layers(
            model, lambda l: _make_quanted(self.config, l))

    def convert(self, model, inplace=False):
        """Freeze: drop the moving-stat updates (eval mode is enough in the
        simulated representation)."""
        if not inplace:
            model = copy.deepcopy(model)
        model.eval()
        return model


class PTQ:
    """Post-training quantization: observe → freeze scales."""

    def __init__(self, config):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)
        return _swap_layers(
            model, lambda l: _make_quanted(self.config, l,
                                           force_observer=True))

    def convert(self, model, inplace=False):
        """Replace observers with fixed fake-quant using observed scales."""
        if not inplace:
            model = copy.deepcopy(model)

        class _Fixed(Layer):
            def __init__(self, scale, bits):
                super().__init__()
                self._s = scale
                self._qmax = 2 ** (bits - 1) - 1

            def forward(self, x):
                return _fake_quant(x, Tensor(np.float32(self._s)),
                                   self._qmax)

        def fix(m):
            for name, sub in list(m.named_children()):
                if isinstance(sub, BaseObserver):
                    setattr(m, name, _Fixed(sub.scales(), sub.quant_bits))
                else:
                    fix(sub)

        fix(model)
        model.eval()
        return model



def quanter(name):
    """Class decorator registering a quanter under `name` (reference
    quantization/factory.py quanter): makes the class discoverable via
    the config factory."""
    def deco(cls):
        _QUANTER_REGISTRY[name] = cls
        return cls

    return deco


_QUANTER_REGISTRY = {}


def weight_only_quantize(model, weight_dtype="int8", group_size=-1,
                         inplace=False):
    """Swap every Linear-family sublayer (nn.Linear and the mpu
    Column/RowParallelLinear, which store the same [in, out] weight) for a
    `nn.quant.WeightOnlyLinear` holding int8/int4 weights + scales — the
    serving-side weight-only pipeline (reference:
    paddle.nn.quant.weight_quantize + PaddleNLP's predictor swap).
    Single-chip serving path: parallel linears are swapped as plain
    linears (quantized sharded serving would re-shard the int8 weights).
    """
    from ..distributed.mpu import ColumnParallelLinear, RowParallelLinear
    from ..nn.layers_common import Linear
    from ..nn.quant import WeightOnlyLinear

    targets = (Linear, ColumnParallelLinear, RowParallelLinear)

    if not inplace:
        model = copy.deepcopy(model)
    # two-phase swap: BUILD every twin first (a failure — e.g. int4 on odd
    # in_features — must not leave the caller's model half-swapped), then
    # install. One quantization pass per weight.
    swaps = []

    def collect(m):
        for name, sub in list(m.named_children()):
            if isinstance(sub, targets):
                swaps.append((m, name, WeightOnlyLinear.from_linear(
                    sub, weight_dtype=weight_dtype, group_size=group_size)))
            else:
                collect(sub)

    collect(model)
    for parent, name, twin in swaps:
        setattr(parent, name, twin)
    return model
