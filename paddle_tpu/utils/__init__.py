"""paddle_tpu.utils: interop + misc utilities.

Role parity: `python/paddle/utils/` — dlpack interop
(`paddle/fluid/framework/dlpack_tensor.cc`), unique_name, deprecated
decorator, download stub, cpp_extension gate, try_import.
"""
from __future__ import annotations

import functools
import itertools
import threading
import warnings

__all__ = ["dlpack", "unique_name", "deprecated", "try_import", "download",
           "cpp_extension", "require_version", "run_check"]


class dlpack:
    """Zero-copy tensor interop via the DLPack protocol (jax arrays speak
    it natively — the DLPack capsule path of the reference)."""

    @staticmethod
    def to_dlpack(x):
        """Return the DLPack protocol object (the modern interchange form:
        consumers call `from_dlpack(obj)` which invokes obj.__dlpack__();
        jax arrays implement the protocol natively)."""
        from ..core.tensor import Tensor

        return x._value if isinstance(x, Tensor) else x

    @staticmethod
    def from_dlpack(obj):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        if not hasattr(obj, "__dlpack__"):
            raise TypeError(
                "from_dlpack needs an object implementing the DLPack "
                "protocol (__dlpack__/__dlpack_device__); raw PyCapsules "
                "from legacy producers are not supported — pass the source "
                "tensor itself")
        return Tensor(jnp.from_dlpack(obj))


class _UniqueNames(threading.local):
    def __init__(self):
        self.counters = {}
        self.prefix = ""


_un = _UniqueNames()


class unique_name:
    @staticmethod
    def generate(key="tmp"):
        c = _un.counters.get(key, 0)
        _un.counters[key] = c + 1
        return f"{_un.prefix}{key}_{c}"

    @staticmethod
    def guard(prefix=None):
        import contextlib

        @contextlib.contextmanager
        def g():
            old_prefix, old_counters = _un.prefix, _un.counters
            _un.prefix = prefix or ""
            _un.counters = {}
            try:
                yield
            finally:
                _un.prefix, _un.counters = old_prefix, old_counters

        return g()

    @staticmethod
    def switch(new_generator=None):
        _un.counters = {}


def deprecated(update_to="", since="", reason="", level=0):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            msg = f"API {fn.__name__!r} is deprecated since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)

        return wrapper

    return deco


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed")


class download:
    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        raise RuntimeError(
            "no network egress in this environment; place weights locally "
            "and load with paddle_tpu.load()")

    get_path_from_url = get_weights_path_from_url


class cpp_extension:
    """Runtime custom-op registration (parity: `paddle.utils.cpp_extension`
    + `custom_operator.cc`). `load` compiles user C++ with g++ (ctypes C
    ABI — pybind11 is not in this image) and registers each exported
    kernel as a paddle op that runs eagerly AND under jit (host callback
    via `jax.pure_callback`), with autodiff when a gradient symbol is
    provided. See `paddle_tpu.native.custom_op` for the ABI contract."""

    @staticmethod
    def load(name, sources, **kwargs):
        from ..native import custom_op

        return custom_op.load(name, sources, **kwargs)

    class CppExtension:
        def __init__(self, sources=None, *a, **kw):
            self.sources = sources or []

    CUDAExtension = CppExtension


def require_version(min_version, max_version=None):
    from .. import __version__

    def tup(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    if tup(__version__) < tup(min_version):
        raise RuntimeError(
            f"requires paddle_tpu>={min_version}, got {__version__}")
    if max_version and tup(__version__) > tup(max_version):
        raise RuntimeError(
            f"requires paddle_tpu<={max_version}, got {__version__}")
    return True


def run_check():
    """paddle.utils.run_check parity: verify the install can compute."""
    import jax
    import numpy as np

    from .. import matmul, to_tensor

    a = to_tensor(np.ones((2, 2), np.float32))
    out = matmul(a, a)
    assert np.allclose(np.asarray(out.numpy()), 2 * np.ones((2, 2)))
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"{n} device(s): {[d.platform for d in jax.devices()]}")
    return True
