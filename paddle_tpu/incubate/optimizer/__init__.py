"""incubate.optimizer parity (`python/paddle/incubate/optimizer/`):
LookAhead, ModelAverage, DistributedFusedLamb.

TPU-first: these are host-side weight post-processors around any inner
optimizer — slow/averaged copies live as jax arrays and the blend math
is a handful of fused elementwise programs, so there is nothing to port
from the reference's fused CUDA kernels.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...optimizer.optimizer import Lamb, Optimizer

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class LookAhead(Optimizer):
    """Lookahead wrapper (incubate/optimizer/lookahead.py): the inner
    optimizer updates fast weights every step; every `k` steps the slow
    weights move alpha of the way toward the fast ones and the fast
    weights reset to the slow copy."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow = None
        self._steps = 0

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        params = self.inner_optimizer._parameter_list
        if self._slow is None:
            self._slow = [jnp.asarray(p._value) for p in params]
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p, s in zip(params, self._slow):
                new_slow = s + self.alpha * (p._value - s)
                p._value = new_slow.astype(p._value.dtype)
            self._slow = [jnp.asarray(p._value) for p in params]

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = {"inner": self.inner_optimizer.state_dict(),
              "steps": self._steps}
        if self._slow is not None:
            sd["slow"] = [np.asarray(s) for s in self._slow]
        return sd

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd.get("inner", {}))
        self._steps = sd.get("steps", 0)
        if "slow" in sd:
            self._slow = [jnp.asarray(s) for s in sd["slow"]]

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Running parameter average (incubate/optimizer/modelaverage.py):
    accumulates weights each step; `apply()` swaps the averaged weights
    in for evaluation, `restore()` puts the live ones back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters or [])
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self._sum = [jnp.zeros_like(p._value) for p in self._parameter_list]
        self._count = 0
        self._backup = None

    def step(self):
        self._count += 1
        for i, p in enumerate(self._parameter_list):
            self._sum[i] = self._sum[i] + p._value.astype(self._sum[i].dtype)
        # bound the window (reference max_average_window behavior)
        if self._count > self.max_w:
            for i, p in enumerate(self._parameter_list):
                self._sum[i] = self._sum[i] * (self.max_w /
                                               float(self._count))
            self._count = self.max_w

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            return
        self._backup = [jnp.asarray(p._value)
                        for p in self._parameter_list]
        for p, s in zip(self._parameter_list, self._sum):
            p._value = (s / self._count).astype(p._value.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._parameter_list, self._backup):
            p._value = b
        self._backup = None

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad() if hasattr(p, "clear_grad") else None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()


class DistributedFusedLamb(Lamb):
    """LAMB whose state sharding comes from the compiled train step
    (reference `distributed_fused_lamb` fuses + shards in CUDA; here
    ZeRO staging in `DistributedTrainStep` shards the moments over dp,
    and XLA fuses the update — same capability, compiler-owned)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True, **kw):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=
                         exclude_from_weight_decay_fn)
