"""paddle.incubate parity surface."""
from . import nn  # noqa: F401
from .distributed.models import moe  # noqa: F401
from .distributed.models.moe import MoELayer  # noqa: F401
