"""paddle.incubate parity surface."""
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .distributed.models import moe  # noqa: F401
from .distributed.models.moe import MoELayer  # noqa: F401


# legacy incubate graph/segment API: aliases of paddle_tpu.geometric
# (the reference moved these to paddle.geometric and keeps incubate
# names for compatibility)
from ..geometric import (  # noqa: F401,E402
    segment_max, segment_mean, segment_min, segment_sum,
)
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401,E402
from ..geometric import reindex_graph as graph_reindex  # noqa: F401,E402
from ..geometric import (  # noqa: F401,E402
    sample_neighbors as graph_sample_neighbors,
)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (incubate.graph_khop_sampler):
    composed from per-hop sample_neighbors + reindex."""
    from .. import geometric as G

    nodes = input_nodes
    all_src, all_dst = [], []
    for k in sample_sizes:
        out = G.sample_neighbors(row, colptr, nodes, sample_size=k)
        neigh, counts = out[0], out[1]
        all_src.append(neigh)
        all_dst.append(nodes)
        nodes = neigh
    reindexed = G.reindex_graph(input_nodes, all_src[0],
                                G.sample_neighbors(
                                    row, colptr, input_nodes,
                                    sample_size=sample_sizes[0])[1])
    return reindexed


def identity_loss(x, reduction="none"):
    import paddle_tpu as P

    return P.identity_loss(x, reduction=reduction)


def softmax_mask_fuse(x, mask, name=None):
    """Fused masked softmax (incubate.softmax_mask_fuse role —
    fused_softmax_mask CUDA kernel): one XLA fusion here."""
    from ..core.dispatch import apply
    import jax
    import jax.numpy as jnp

    def f(xv, mv):
        return jax.nn.softmax(xv.astype(jnp.float32)
                              + mv.astype(jnp.float32),
                              axis=-1).astype(xv.dtype)

    return apply("softmax_mask_fuse", f, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (fused_softmax_mask_upper_triangle role)."""
    from ..core.dispatch import apply
    import jax
    import jax.numpy as jnp

    def f(xv):
        s = xv.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, xv.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(xv.dtype)

    return apply("softmax_mask_fuse_upper_triangle", f, x)
