"""paddle.incubate parity surface."""
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .distributed.models import moe  # noqa: F401
from .distributed.models.moe import MoELayer  # noqa: F401
