"""paddle_tpu.incubate.autograd — functional/prim autograd surface.

Role parity: `python/paddle/incubate/autograd/__init__.py` (vjp, jvp,
Jacobian, Hessian, enable_prim, disable_prim, forward_grad, grad). The
reference's prim system decomposes composite ops into primitive vjp/jvp
rules so its static compiler can differentiate and fuse
(`primapi.py:25,108`); on this stack jax IS the primitive system — every
op body already lowers to differentiable lax primitives — so
enable/disable_prim only flips the compatibility flag the reference
exposes, and forward-mode AD comes straight from `jax.jvp`.
"""
from __future__ import annotations

from ...autograd.functional import hessian as Hessian
from ...autograd.functional import jacobian as Jacobian
from ...autograd.functional import jvp, vjp

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled", "forward_grad", "grad"]

_prim_state = {"enabled": False}


def enable_prim():
    """Compatibility flag (reference switches static AD to primitive-op
    decomposition; XLA always differentiates primitives here)."""
    _prim_state["enabled"] = True


def disable_prim():
    _prim_state["enabled"] = False


def prim_enabled():
    return _prim_state["enabled"]


def forward_grad(func, xs, v=None):
    """Forward-mode derivative of `func` at `xs` along tangents `v`
    (reference primapi.forward_grad role, functional form: the reference
    operates on static-graph output/input Variables; here forward-mode AD
    is `jax.jvp` over the same op bodies). Returns (outputs, tangents)."""
    return jvp(func, xs, v)


def grad(func, xs, v=None):
    """Reverse-mode gradients of `func` at `xs` (reference primapi.grad
    role, functional form). v: optional output cotangents; defaults to
    ones. Returns the gradient(s) with the structure of `xs`."""
    _, grads = vjp(func, xs, v)
    return grads
