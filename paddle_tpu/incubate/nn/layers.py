"""incubate.nn fused Layer zoo.

Role parity: `python/paddle/incubate/nn/layer/fused_transformer.py`
(FusedMultiHeadAttention `:196`, FusedFeedForward `:502`,
FusedTransformerEncoderLayer `:728`, FusedMultiTransformer `:1025`,
FusedBiasDropoutResidualLayerNorm `:83`), `fused_linear.py`,
`fused_dropout_add.py`, `fused_ec_moe.py`.

TPU-first: the reference backs these with monolithic CUDA fused kernels
(`fused_attention_op.cu`, `fused_feedforward_op.cu`); here each layer
composes this framework's fused functional tier — Pallas flash attention
/ fused (residual+bias+)norm on TPU, XLA-fused jnp elsewhere — which the
compiler fuses across. The module/parameter structure mirrors the
reference so state dicts and construction code port over.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn import functional as F
from ..nn import functional as IF

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer", "FusedEcMoe",
]


class FusedLinear(nn.Layer):
    """Linear whose matmul+bias-add XLA emits as one fused op
    (fused_gemm_epilogue role)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape)
        self.bias = None if bias_attr is False \
            else self.create_parameter([out_features], is_bias=True)

    def forward(self, x):
        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self.transpose_weight)


class FusedDropoutAdd(nn.Layer):
    """y = x + dropout(residual-input) in one fused op."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return IF.fused_dropout_add(x, y, p=self.p,
                                    training=self.training,
                                    mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """out = layer_norm(residual + dropout(x + bias)) in one pass."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        if self.dropout_rate and self.training:
            x = F.dropout(x + self.linear_bias, p=self.dropout_rate)
            out = IF.fused_layer_norm(
                x, self.ln_scale, self.ln_bias, epsilon=self.epsilon,
                residual=residual)
        else:
            out = IF.fused_layer_norm(
                x, self.ln_scale, self.ln_bias, epsilon=self.epsilon,
                bias=self.linear_bias, residual=residual)
        return out[0] if isinstance(out, (tuple, list)) else out


class FusedMultiHeadAttention(nn.Layer):
    """Pre/post-LN fused self-attention block: qkv proj → flash attention
    → out proj → dropout+residual(+LN)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        assert not need_weights, "need_weights is not supported (reference)"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        # reference layout: qkv_weight [3, H, D, hidden]
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim])
        self.qkv_bias = None if qkv_bias_attr is False else \
            self.create_parameter([3, num_heads, self.head_dim],
                                  is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim])
        self.linear_bias = None if linear_bias_attr is False else \
            self.create_parameter([embed_dim], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=nn.initializer.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        x = query
        residual = x
        if self.normalize_before:
            x = IF.fused_layer_norm(x, self.pre_ln_scale, self.pre_ln_bias,
                                    epsilon=self.epsilon)
            x = x[0] if isinstance(x, (tuple, list)) else x
        b, s, h = x.shape
        # qkv: [B,S,H*D*3] via the [3,H,D,hidden] weight
        w = self.qkv_weight.reshape([3 * h, h])
        qkv = x.matmul(w, transpose_y=True)
        if self.qkv_bias is not None:
            qkv = qkv + self.qkv_bias.reshape([3 * h])
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            is_causal=False, training=self.training)
        out = out.reshape([b, s, h]).matmul(self.linear_weight)
        if self.linear_bias is not None:
            out = out + self.linear_bias
        if self.dropout_rate and self.training:
            out = F.dropout(out, p=self.dropout_rate)
        out = out + residual
        if not self.normalize_before:
            out = IF.fused_layer_norm(out, self.ln_scale, self.ln_bias,
                                      epsilon=self.epsilon)
            out = out[0] if isinstance(out, (tuple, list)) else out
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward])
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model])
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=nn.initializer.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=nn.initializer.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        x = src
        if self.normalize_before:
            x = IF.fused_layer_norm(x, self.ln1_scale, self.ln1_bias,
                                    epsilon=self.epsilon)
            x = x[0] if isinstance(x, (tuple, list)) else x
        x = IF.fused_linear_activation(
            x, self.linear1_weight, self.linear1_bias,
            activation=self.activation)
        if self.act_dropout_rate and self.training:
            x = F.dropout(x, p=self.act_dropout_rate)
        x = x.matmul(self.linear2_weight) + self.linear2_bias
        if self.dropout_rate and self.training:
            x = F.dropout(x, p=self.dropout_rate)
        x = x + residual
        if not self.normalize_before:
            x = IF.fused_layer_norm(x, self.ln2_scale, self.ln2_bias,
                                    epsilon=self.epsilon)
            x = x[0] if isinstance(x, (tuple, list)) else x
        return x


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate
            if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """N fused decoder layers with one shared forward (the reference's
    inference-serving block, `fused_multi_transformer_op`): pre-LN
    self-attention (causal) + FFN, optional KV caches."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None, epsilon=1e-5,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1,
                 name=None):
        super().__init__()
        assert normalize_before, \
            "FusedMultiTransformer is pre-LN (reference contract)"
        if num_layers < 0:
            num_layers = 1
        self.layers = nn.LayerList()
        for _ in range(num_layers):
            blk = nn.Sequential()
            blk.attn = FusedMultiHeadAttention(
                embed_dim, num_heads, dropout_rate=dropout_rate,
                attn_dropout_rate=dropout_rate, normalize_before=True,
                epsilon=epsilon)
            blk.ffn = FusedFeedForward(
                embed_dim, dim_feedforward, dropout_rate=dropout_rate,
                activation=activation, normalize_before=True,
                epsilon=epsilon)
            self.layers.append(blk)

    def forward(self, src, attn_mask=None, caches=None, seq_lens=None,
                time_step=None):
        x = src
        for blk in self.layers:
            x = blk.attn(x, attn_mask=attn_mask)
            x = blk.ffn(x)
        return x


class FusedEcMoe(nn.Layer):
    """Expert-choice MoE block (fused_ec_moe role): gate → per-expert
    two-layer FFN, batched over experts with einsum (one XLA fusion)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError(f"unsupported act_type {act_type}")
        self.act_type = act_type
        self.gate = nn.Linear(hidden_size, num_experts)
        self.e_w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size])
        self.e_b1 = self.create_parameter([num_experts, 1, inter_size],
                                          is_bias=True)
        self.e_w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size])
        self.e_b2 = self.create_parameter([num_experts, 1, hidden_size],
                                          is_bias=True)

    def forward(self, x, gate=None):
        from ...core.dispatch import apply

        gate_logits = self.gate(x) if gate is None else gate

        def f(xv, gl, w1, b1, w2, b2):
            import jax
            import jax.numpy as jnp

            probs = jax.nn.softmax(gl, axis=-1)          # [B,S,E]
            h = jnp.einsum("bsh,ehi->ebsi", xv, w1) + b1[:, None]
            h = jax.nn.gelu(h) if self.act_type == "gelu" \
                else jax.nn.relu(h)
            out = jnp.einsum("ebsi,eih->ebsh", h, w2) + b2[:, None]
            return jnp.einsum("ebsh,bse->bsh", out,
                              probs.astype(out.dtype))

        return apply("fused_ec_moe", f, x, gate_logits, self.e_w1,
                     self.e_b1, self.e_w2, self.e_b2)
