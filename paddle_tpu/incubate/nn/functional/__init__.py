"""incubate.nn.functional parity — fused-op API surface
(`python/paddle/incubate/nn/functional/`): on TPU these route to the Pallas
tier or XLA-fused jnp bodies (same semantics, compiler does the fusing)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....nn.functional.attention import (  # noqa: F401
    fused_rotary_position_embedding,
)
from ....nn.functional.norm import rms_norm as _rms_norm
from ....nn.functional import layer_norm as _layer_norm
from ....core.dispatch import apply, op
from ....core.tensor import Tensor

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "fused_bias_act", "fused_linear", "fused_linear_activation",
    "swiglu", "fused_dropout_add", "masked_multihead_attention",
    "variable_length_memory_efficient_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    """fused_rms_norm parity (residual-add + bias + rmsnorm in one op)."""
    if quant_scale not in (-1, None):
        raise NotImplementedError(
            "fused_rms_norm: quantized output (quant_scale) is not "
            "supported — quantize with nn.quant after the norm")
    def f(xv, w, b, bias_v, res):
        from ....ops.pallas.fused_norm import (
            fused_norm_available, fused_norm_pallas,
        )

        if begin_norm_axis in (-1, xv.ndim - 1) and \
                fused_norm_available(xv, w, b):
            return fused_norm_pallas(xv, w, b, bias_v, res,
                                     eps=epsilon, kind="rms")
        if bias_v is not None:
            xv = xv + bias_v
        if res is not None:
            xv = xv + res
        out = xv.astype(jnp.float32)
        ms = jnp.mean(jnp.square(out), axis=-1, keepdims=True)
        out = (out * jax.lax.rsqrt(ms + epsilon)).astype(xv.dtype)
        out = out * w
        if b is not None:
            out = out + b
        if res is not None or bias_v is not None:
            return out, xv
        return out

    return apply("fused_rms_norm", f, x, norm_weight, norm_bias, bias,
                 residual)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    def f(xv, w, b, bias_v, res):
        from ....ops.pallas.fused_norm import (
            fused_norm_available, fused_norm_pallas,
        )

        if begin_norm_axis in (-1, xv.ndim - 1) and \
                fused_norm_available(xv, w, b):
            return fused_norm_pallas(xv, w, b, bias_v, res,
                                     eps=epsilon, kind="ln")
        if bias_v is not None:
            xv = xv + bias_v
        if res is not None:
            xv = xv + res
        x32 = xv.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        out = ((x32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(xv.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        if res is not None or bias_v is not None:
            return out, xv
        return out

    return apply("fused_layer_norm", f, x, norm_weight, norm_bias, bias,
                 residual)


@op("fused_bias_act")
def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    acts = {"gelu": lambda v: jax.nn.gelu(v),
            "relu": lambda v: jnp.maximum(v, 0),
            "silu": lambda v: v * jax.nn.sigmoid(v),
            "swiglu": lambda v: _swiglu_val(v)}
    return acts[act_method](x)


def _swiglu_val(v):
    a, b = jnp.split(v, 2, axis=-1)
    return a * jax.nn.sigmoid(a) * b


@op("swiglu")
def swiglu(x, y=None, name=None):
    if y is None:
        return _swiglu_val(x)
    return x * jax.nn.sigmoid(x) * y


@op("fused_linear")
def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        weight = weight.T
    out = x @ weight
    if bias is not None:
        out = out + bias
    return out


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    if trans_x:
        # matrix-dims transpose only (reference fused_gemm_epilogue
        # semantics); .T on ndim>2 would reverse ALL dims
        x = x.mT if getattr(x, "ndim", 2) > 2 else x.T
    out = fused_linear(x, y, bias, trans_y)
    from ....nn import functional as F

    return {"gelu": F.gelu, "relu": F.relu}[activation](out)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn import functional as F

    return F.dropout(x, p, training=training, mode=mode) + y


def masked_multihead_attention(x, cache_kv=None, src_mask=None,
                               sequence_lengths=None, rotary_embs=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", name=None):
    """Decode-step attention over a KV cache (parity:
    `incubate.nn.functional.masked_multihead_attention`, reference kernel
    `paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu`).

    x: [B, 3*H*D] fused qkv of the CURRENT token. cache_kv: [2, B, H, S, D].
    sequence_lengths: [B] number of tokens already in the cache (write
    position). Returns (out [B, H*D], updated cache_kv).

    TPU-first: the cache update is a static-shape scatter
    (`.at[b, :, pos].set`) and attention runs over the full cache with a
    position mask — fixed shapes every step, so the decode loop compiles
    once; XLA fuses mask+softmax+weighted-sum into the two einsums.
    """
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    unsupported = {"rotary_embs": rotary_embs,
                   "beam_cache_offset": beam_cache_offset,
                   "qkv_out_scale": qkv_out_scale, "out_shift": out_shift,
                   "out_smooth": out_smooth}
    bad = [k for k, v in unsupported.items() if v is not None]
    if rotary_emb_dims:
        bad.append("rotary_emb_dims")
    if bad:
        raise NotImplementedError(
            f"masked_multihead_attention: {bad} not supported yet — apply "
            "RoPE before the qkv fuse (models.llama does) and dequant "
            "outside")
    _, B, H, S, D = cache_kv.shape

    if sequence_lengths is None:
        raise ValueError("sequence_lengths ([B] int32 write positions) is "
                         "required in this implementation")

    def f(xv, cache, pos, mask):
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        pos = pos.reshape(-1).astype(jnp.int32)
        bidx = jnp.arange(B)
        kcache = cache[0].at[bidx, :, pos, :].set(k)
        vcache = cache[1].at[bidx, :, pos, :].set(v)
        if mask is None:
            from ....ops.pallas.decode_attention import (
                decode_attention, decode_attention_available,
            )

            if decode_attention_available(cache.shape):
                out = decode_attention(q, kcache, vcache, pos)
                return out.reshape(B, H * D), jnp.stack([kcache, vcache])
        valid = (jnp.arange(S)[None, None, :]
                 <= pos[:, None, None])                       # [B,1,S]
        scores = jnp.einsum("bhd,bhsd->bhs", q, kcache) \
            * (1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))).astype(q.dtype)
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, scores.dtype)
        scores = jnp.where(valid, scores, neg)
        if mask is not None:
            scores = scores + mask.reshape(B, 1, -1)[:, :, :S]
        p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
            vcache.dtype)
        out = jnp.einsum("bhs,bhsd->bhd", p, vcache)
        return out.reshape(B, H * D), jnp.stack([kcache, vcache])

    return apply("masked_multihead_attention", f, x, cache_kv,
                 sequence_lengths, src_mask)


def variable_length_memory_efficient_attention(query, key, value,
                                               seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """Reference `variable_length_memory_efficient_attention` (cutlass
    memory-efficient varlen kernel role): [B, H, S, D] inputs, per-row
    valid lengths. Keys/values beyond `kv_seq_lens[b]` never contribute
    (additive -inf fold); query rows beyond `seq_lens[b]` compute
    don't-care outputs exactly like the reference kernel. Explicit
    `scale` folds into q."""
    import math as _math

    from ....core.dispatch import apply

    def f(qv, kv, vv, sl, kvl, mk):
        b, h, sq, d = qv.shape
        sk = kv.shape[2]
        if scale is not None:
            qv = qv * jnp.asarray(scale * _math.sqrt(d), qv.dtype)
        add = None
        if mk is not None:
            add = mk.astype(jnp.float32)
        if kvl is not None:
            valid_k = jnp.arange(sk)[None, None, None, :] < \
                jnp.reshape(kvl, (b, 1, 1, 1))
            lmask = jnp.where(valid_k, 0.0, -1e30).astype(jnp.float32)
            add = lmask if add is None else add + lmask
        from ....ops.pallas.flash_attention import _ref_attention

        # [B,H,S,D] -> [B,S,H,D] for the attention body
        out = _ref_attention(jnp.swapaxes(qv, 1, 2),
                             jnp.swapaxes(kv, 1, 2),
                             jnp.swapaxes(vv, 1, 2), add, causal)
        return jnp.swapaxes(out, 1, 2)

    return apply("variable_length_memory_efficient_attention", f,
                 query, key, value, seq_lens, kv_seq_lens, mask)



def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    if transpose_x:
        x = x.T
    return fused_linear(x, y, bias, transpose_weight=transpose_y)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode='upscale_in_train',
                               ring_id=-1, add_residual=True, name=None):
    """Functional fused MHA (fused_attention_op role): qkv proj (packed
    [3,H,D,hidden] weight) -> flash/sdpa -> out proj -> residual(+LN)."""
    from ....nn.functional import (
        dropout as _dropout, scaled_dot_product_attention as _sdpa,
    )

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention: cache_kv decode is served by "
            "masked_multihead_attention / block_multihead_attention")
    residual = x
    if pre_layer_norm and ln_scale is not None or pre_ln_scale is not None:
        out = fused_layer_norm(x, pre_ln_scale, pre_ln_bias,
                               epsilon=pre_ln_epsilon)
        x = out[0] if isinstance(out, (tuple, list)) else out
    b, s, h = x.shape
    nh = qkv_weight.shape[1]
    hd = qkv_weight.shape[2]
    w = qkv_weight.reshape([3 * h, h])
    qkv = x.matmul(w, transpose_y=True)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape([3 * h])
    qkv = qkv.reshape([b, s, 3, nh, hd])
    q, k, v = qkv.unbind(axis=2)
    out = _sdpa(q, k, v, attn_mask=attn_mask,
                dropout_p=attn_dropout_rate if training else 0.0,
                is_causal=False, training=training)
    out = out.reshape([b, s, h]).matmul(linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate and training:
        out = _dropout(out, p=dropout_rate, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm and ln_scale is not None:
        o2 = fused_layer_norm(out, ln_scale, ln_bias, epsilon=ln_epsilon)
        out = o2[0] if isinstance(o2, (tuple, list)) else o2
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode='upscale_in_train', ring_id=-1, name=None):
    from ....nn.functional import dropout as _dropout

    residual = x
    if pre_layer_norm and ln1_scale is not None:
        out = fused_layer_norm(x, ln1_scale, ln1_bias, epsilon=ln1_epsilon)
        x = out[0] if isinstance(out, (tuple, list)) else out
    x = fused_linear_activation(x, linear1_weight, linear1_bias,
                                activation=activation)
    if dropout1_rate and training:
        x = _dropout(x, p=dropout1_rate, mode=mode)
    x = x.matmul(linear2_weight)
    if linear2_bias is not None:
        x = x + linear2_bias
    if dropout2_rate and training:
        x = _dropout(x, p=dropout2_rate, mode=mode)
    x = x + residual
    if not pre_layer_norm and ln2_scale is not None:
        out = fused_layer_norm(x, ln2_scale, ln2_bias, epsilon=ln2_epsilon)
        x = out[0] if isinstance(out, (tuple, list)) else out
    return x


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False, mode=None,
                            trans_qkvw=True, ring_id=-1, name=None):
    """N pre-LN decoder layers over packed per-layer weight lists
    (fused_multi_transformer_op role)."""
    if cache_kvs is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer: cached decode is served by "
            "masked_multihead_attention / models.generate")
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer: trans_qkvw=False (untransposed qkv "
            "weights) is not supported — pass [3, H, D, hidden] weights")
    out = x
    for i in range(len(qkv_weights)):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i], pre_layer_norm=True,
            pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training,
            pre_ln_epsilon=epsilon, ln_epsilon=epsilon)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=True, training=training,
            ln1_epsilon=epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode='upscale_in_train',
                                           name=None):
    from ....nn.functional import dropout as _dropout

    if bias is not None:
        x = x + bias
    if dropout_rate and training:
        x = _dropout(x, p=dropout_rate, mode=mode)
    out = fused_layer_norm(x, ln_scale, ln_bias, epsilon=ln_epsilon,
                           residual=residual)
    return out[0] if isinstance(out, (tuple, list)) else out


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    from ....core.dispatch import apply
    import jax
    import jax.numpy as jnp

    def f(xv, gl, w1, b1, w2, b2):
        probs = jax.nn.softmax(gl, axis=-1)
        h = jnp.einsum("bsh,ehi->ebsi", xv, w1) + b1
        h = jax.nn.gelu(h) if act_type == "gelu" else jax.nn.relu(h)
        out = jnp.einsum("ebsi,eih->ebsh", h, w2) + b2
        return jnp.einsum("ebsh,bse->bsh", out, probs.astype(out.dtype))

    return apply("fused_ec_moe", f, x, gate, bmm0_weight, bmm0_bias,
                 bmm1_weight, bmm1_bias)


def block_multihead_attention(qkv, key_cache, value_cache,
                              seq_lens_encoder, seq_lens_decoder,
                              seq_lens_this_time, padding_offsets=None,
                              cum_offsets=None, cu_seqlens_q=None,
                              cu_seqlens_k=None, block_tables=None,
                              pre_key_cache=None, pre_value_cache=None,
                              cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None,
                              qkv_out_scale=None, qkv_bias=None,
                              out_shift=None, out_smooth=None,
                              max_enc_len_this_time=None,
                              max_dec_len_this_time=None, rope_emb=None,
                              mask=None, tgt_mask=None, max_seq_len=-1,
                              block_size=64, use_neox_style=False,
                              name=None, **kwargs):
    """Paged (block) KV-cache decode attention
    (`block_multi_head_attention_kernel.cu` role): each sequence's cache
    lives in `block_size`-token blocks scattered through a shared block
    pool, addressed by `block_tables` [B, max_blocks_per_seq].

    Decode-step subset (one new token per sequence — the serving hot
    path): the new token's K/V are written into the current block slot,
    and attention runs over the gathered per-sequence blocks with a
    validity mask from `seq_lens_decoder`. Quant/smooth scale inputs are
    not supported (no int8 cache tier) and raise loudly.

    qkv: [B, 3*H*D]; key_cache/value_cache: [num_blocks, H, block_size,
    D]; returns (out [B, H*D], key_cache, value_cache) with the caches
    functionally updated.
    """
    if any(s is not None for s in (cache_k_quant_scales,
                                   cache_v_quant_scales,
                                   cache_k_dequant_scales,
                                   cache_v_dequant_scales, qkv_out_scale,
                                   out_shift, out_smooth)):
        raise NotImplementedError(
            "block_multihead_attention: int8/smooth-quant cache scales "
            "are not supported (no int8 cache tier in this build)")
    if rope_emb is not None or tgt_mask is not None or \
            pre_key_cache is not None or pre_value_cache is not None:
        raise NotImplementedError(
            "block_multihead_attention: rope_emb/tgt_mask/pre-caches are "
            "not supported — apply RoPE before the qkv fuse and use "
            "mask= for attention masking")
    # decode-step subset: a PREFILL batch (nonzero encoder lens) would
    # silently compute garbage — fail loudly when detectable (concrete
    # eager values; traced values are the caller's contract)
    if seq_lens_encoder is not None:
        try:
            import numpy as _np

            enc = _np.asarray(seq_lens_encoder.numpy()
                              if hasattr(seq_lens_encoder, "numpy")
                              else seq_lens_encoder)
            if (enc > 0).any():
                raise NotImplementedError(
                    "block_multihead_attention: prefill (nonzero "
                    "seq_lens_encoder) is not supported — prefill with "
                    "the dense flash path, decode here")
        except NotImplementedError:
            raise
        except Exception as e:
            # probe-only: un-inspectable seq_lens_encoder falls through
            # to the decode path — but not silently
            from ....observability import flight as _flight

            _flight.record("block_mha.prefill_probe_failed",
                           error=repr(e))

    from ....core.dispatch import apply
    import jax
    import jax.numpy as jnp

    def f(qkv_v, kc, vc, dec_lens, bt, qb):
        b = qkv_v.shape[0]
        nb, h, bs, d = kc.shape
        if qb is not None:
            qkv_v = qkv_v + qb.reshape(-1)
        qkv3 = qkv_v.reshape(b, 3, h, d)
        q, k_new, v_new = qkv3[:, 0], qkv3[:, 1], qkv3[:, 2]
        lens = dec_lens.reshape(-1).astype(jnp.int32)   # tokens already cached
        # write the new token at position lens[b] in its sequence:
        blk_idx = lens // bs
        slot = lens % bs
        phys = jnp.take_along_axis(bt, blk_idx[:, None], axis=1)[:, 0]
        kc = kc.at[phys, :, slot].set(k_new)
        vc = vc.at[phys, :, slot].set(v_new)
        # gather each sequence's blocks: [B, max_blocks, H, bs, D]
        kb = kc[bt]
        vb = vc[bt]
        max_blocks = bt.shape[1]
        s_max = max_blocks * bs
        kseq = jnp.moveaxis(kb, 2, 1).reshape(b, h, s_max, d)
        vseq = jnp.moveaxis(vb, 2, 1).reshape(b, h, s_max, d)
        scale = 1.0 / (d ** 0.5)
        logits = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                            kseq.astype(jnp.float32)) * scale
        valid = jnp.arange(s_max)[None, :] <= lens[:, None]
        logits = jnp.where(valid[:, None, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", probs,
                         vseq.astype(jnp.float32))
        return (out.reshape(b, h * d).astype(qkv_v.dtype), kc, vc)

    return apply("block_multihead_attention", f, qkv, key_cache,
                 value_cache, seq_lens_decoder, block_tables, qkv_bias)


__all__ += [
    "fused_matmul_bias", "fused_multi_head_attention", "fused_feedforward",
    "fused_multi_transformer", "fused_bias_dropout_residual_layer_norm",
    "fused_ec_moe", "block_multihead_attention",
]
