"""ASP: automatic (n:m) structured sparsity.

Role parity: `python/paddle/incubate/asp/asp.py` (SURVEY §2.8) — compute
n:m sparse masks for weights, prune a model, and keep the masks applied
across optimizer steps via `decorate`.

TPU note: the reference targets Ampere 2:4 sparse tensor cores; TPUs have
no structured-sparsity MXU mode, so the win here is model-size/regularizer
parity — masks are plain elementwise multiplies that XLA fuses into the
matmul's producer. The workflow API is kept identical.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

_supported_layers_cache = {}
_masks = {}  # id(param) -> jnp mask


def calculate_density(mat):
    arr = np.asarray(mat._value if isinstance(mat, Tensor) else mat)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d_block(block, n, m):
    """Keep the n largest-|.| entries of an m-block."""
    keep = np.argsort(-np.abs(block))[:n]
    mask = np.zeros_like(block, dtype=bool)
    mask[keep] = True
    return mask


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask along the last axis (numpy offline computation, as the
    reference's mask calc is)."""
    arr = np.asarray(tensor._value if isinstance(tensor, Tensor) else tensor)
    orig_shape = arr.shape
    flat = arr.reshape(-1, orig_shape[-1])
    cols = orig_shape[-1]
    if cols % m != 0:
        raise ValueError(f"last dim {cols} not divisible by m={m}")
    blocks = flat.reshape(flat.shape[0], cols // m, m)
    mask = np.zeros_like(blocks, dtype=bool)
    for i in range(blocks.shape[0]):
        for j in range(blocks.shape[1]):
            mask[i, j] = _mask_1d_block(blocks[i, j], n, m)
    return Tensor(mask.reshape(orig_shape).astype(arr.dtype))


def check_sparsity(mat, n=2, m=4, func_name="check_1d"):
    arr = np.asarray(mat._value if isinstance(mat, Tensor) else mat)
    flat = arr.reshape(-1, arr.shape[-1])
    if arr.shape[-1] % m != 0:
        return False
    blocks = flat.reshape(flat.shape[0], -1, m)
    nnz = (blocks != 0).sum(axis=-1)
    return bool((nnz <= n).all())


def _prunable_params(model):
    from ..nn.layers_common import Linear
    from ..nn.layers_conv_pool import Conv2D

    out = []
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (Linear, Conv2D)) and hasattr(layer, "weight"):
            w = layer.weight
            if w.ndim >= 2 and w.shape[-1] % 4 == 0:
                out.append(w)
    return out


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to the supported weights; remember masks so
    `decorate`d optimizers re-apply them after each step."""
    pruned = {}
    for w in _prunable_params(model):
        mask = create_mask(w, func_name=mask_algo, n=n, m=m)
        mval = jnp.asarray(mask._value)
        w._value = w._value * mval.astype(w._value.dtype)
        if with_mask:
            _masks[id(w)] = mval
        pruned[id(w)] = mask
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply the recorded masks after updates
    (parity: ASPHelper._decorate / OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for p in optimizer._parameter_list or []:
            mask = _masks.get(id(p))
            if mask is not None:
                p._value = p._value * mask.astype(p._value.dtype)

    optimizer.step = step
    return optimizer


def reset_excluded_layers(model=None):
    _masks.clear()


def set_excluded_layers(model, layer_names):
    # name-based exclusion: drop masks of matching sublayers
    for name, sub in model.named_sublayers():
        if name in layer_names and hasattr(sub, "weight"):
            _masks.pop(id(sub.weight), None)
