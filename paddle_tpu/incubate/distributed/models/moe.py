"""Mixture-of-Experts with expert parallelism.

Role parity: `MoELayer` (`python/paddle/incubate/distributed/models/moe/
moe_layer.py:263`) with gshard/switch gates (`gate/`), and the
global_scatter/global_gather alltoall dispatch ops
(`python/paddle/distributed/utils/moe_utils.py:20,153`).

TPU-first formulation: experts are ONE batched weight tensor
[num_experts, ...] whose expert dim is annotated over the expert-parallel
mesh axis; routing uses the GShard dense dispatch/combine einsum form
(capacity-bucketed one-hots). Under jit, XLA lowers the dispatch einsum
against ep-sharded experts to exactly the all_to_all the reference codes by
hand — and fuses the surrounding math. Top-1 (Switch) and top-2 (GShard)
gates with load-balancing aux loss.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ....core.dispatch import apply
from ....nn.initializer import XavierUniform
from ....nn.layer_base import Layer

__all__ = ["MoELayer", "GShardGate", "SwitchGate", "global_scatter",
           "global_gather"]


def _top2_gating(logits, capacity, key=None):
    """GShard top-2 routing. logits: [T, E] f32.
    Returns combine [T, E, C], dispatch(bool) [T, E, C], aux loss."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
    probs_wo1 = probs * (1 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)

    # load-balance aux loss (gshard eq.)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E

    # positions within each expert's capacity buffer
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1
    mask1 = mask1 * (pos1 < capacity)
    pos1 = jnp.sum(pos1 * mask1, axis=-1)

    used1 = jnp.sum(mask1, axis=0)
    pos2 = (jnp.cumsum(mask2, axis=0) - 1 + used1[None]) * mask2
    mask2 = mask2 * (pos2 < capacity) * (mask2 > 0)
    pos2 = jnp.sum(pos2 * mask2, axis=-1)

    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    cap_oh1 = jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                             dtype=probs.dtype)
    cap_oh2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity,
                             dtype=probs.dtype)
    combine = (g1[:, None, None] * mask1[:, :, None] * cap_oh1[:, None, :] +
               g2[:, None, None] * mask2[:, :, None] * cap_oh2[:, None, :])
    dispatch = combine > 0
    return combine, dispatch, aux


def _top1_gating(logits, capacity):
    """Switch routing (top-1)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    mask = mask * (pos < capacity)
    pos = jnp.sum(pos * mask, axis=-1)
    gate = jnp.sum(probs * mask, axis=-1)
    cap_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=probs.dtype)
    combine = gate[:, None, None] * mask[:, :, None] * cap_oh[:, None, :]
    return combine, combine > 0, aux


class _GateBase(Layer):
    TOP_K = 2

    def __init__(self, d_model, num_experts, capacity_factor=1.5):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=XavierUniform())

    def capacity(self, num_tokens):
        return max(4, int(self.capacity_factor * self.TOP_K * num_tokens /
                          self.num_experts))


class GShardGate(_GateBase):
    TOP_K = 2

    def route(self, xv, capacity):
        logits = (xv @ self.weight._value).astype(jnp.float32)
        return _top2_gating(logits, capacity)


class SwitchGate(_GateBase):
    TOP_K = 1

    def route(self, xv, capacity):
        logits = (xv @ self.weight._value).astype(jnp.float32)
        return _top1_gating(logits, capacity)


class MoELayer(Layer):
    """d_model -> num_experts FFN experts -> d_model, top-k routed.

    `ep_axis` names the mesh axis the expert dim is sharded over (defaults
    to "mp" — the reference's distinct expert group maps to whichever axis
    the deployment dedicates)."""

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.5, ep_axis="mp", activation=None,
                 recompute_interval=0, name=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        if isinstance(gate, str):
            gate = {"gshard": GShardGate, "switch": SwitchGate}[gate](
                d_model, num_experts, capacity_factor)
        self.gate = gate
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden],
            default_initializer=XavierUniform())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=XavierUniform())
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        # expert dim over the ep axis: dispatch einsum becomes all_to_all
        self.w1.dist_attr = (ep_axis, None, None)
        self.b1.dist_attr = (ep_axis, None, None)
        self.w2.dist_attr = (ep_axis, None, None)
        self.b2.dist_attr = (ep_axis, None, None)
        self.aux_loss = None

    def forward(self, x):
        orig_shape = x.shape
        capacity = self.gate.capacity(int(np.prod(orig_shape[:-1])))

        def f(xv, gw, w1, b1, w2, b2):
            flat = xv.reshape(-1, xv.shape[-1])
            logits = (flat @ gw).astype(jnp.float32)
            if isinstance(self.gate, SwitchGate):
                combine, dispatch, aux = _top1_gating(logits, capacity)
            else:
                combine, dispatch, aux = _top2_gating(logits, capacity)
            combine = combine.astype(xv.dtype)
            # dispatch: [T,E,C] x [T,M] -> [E,C,M]  (alltoall under ep)
            buf = jnp.einsum("tec,tm->ecm", dispatch.astype(xv.dtype), flat)
            h = jax.nn.gelu(jnp.einsum("ecm,emh->ech", buf, w1) + b1)
            out_e = jnp.einsum("ech,ehm->ecm", h, w2) + b2
            # combine back: [T,E,C] x [E,C,M] -> [T,M]
            out = jnp.einsum("tec,ecm->tm", combine, out_e)
            return out.reshape(xv.shape), aux.astype(jnp.float32)

        out, aux = apply("moe_layer", f, x, self.gate.weight, self.w1,
                         self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return out


def global_scatter(x, local_count, global_count, group=None):
    """moe_utils.global_scatter parity: explicit token exchange. On TPU the
    dense-dispatch path above subsumes this; kept for API compatibility via
    alltoall over the group axis."""
    from ....distributed.collective import alltoall_single

    return alltoall_single(None, x, group=group)


def global_gather(x, local_count, global_count, group=None):
    from ....distributed.collective import alltoall_single

    return alltoall_single(None, x, group=group)
