"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas.

Top-level namespace mirrors `paddle.*` (tensor ops, nn, optimizer, amp, io,
jit, autograd, distributed, vision, metric) while the execution model is
TPU-first: eager ops dispatch pure-jnp kernels with a tape autograd; the
performance path traces the same code into XLA via `jit.to_static`; all
parallelism rides `jax.sharding` meshes + collectives over ICI/DCN.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .core import dtypes as _dtypes_mod
from .core.dtypes import (  # noqa: F401
    bfloat16, bool_, complex128, complex64, float16, float32, float64,
    get_default_dtype, int16, int32, int64, int8, set_default_dtype, uint8,
)
from .core.tensor import Parameter, Tensor  # noqa: F401
from .core import flags as _flags
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.rng import seed, get_rng_state, set_rng_state  # noqa: F401
from .core.engine import grad  # noqa: F401

from .ops import *  # noqa: F401,F403
from .ops import is_tensor, add_n, accuracy  # noqa: F401
from .ops.manipulation import shape_op as shape  # noqa: F401

# `from .ops import *` leaks the op-submodule names (ops.linalg etc.) into
# this namespace; drop them so `paddle_tpu.linalg` resolves to the dedicated
# namespace module below, as `paddle.linalg` does in the reference.
for _leak in ("creation", "math", "reduction", "manipulation", "linalg",
              "logic"):
    globals().pop(_leak, None)
del _leak

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import framework  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import vision  # noqa: F401


def __getattr__(name):
    # heavier subsystems load lazily (they import jax mesh machinery)
    import importlib

    lazy = {"distributed", "hapi", "incubate", "models", "profiler",
            "distribution", "sparse", "text", "audio", "quantization",
            "geometric", "fft", "signal", "linalg", "regularizer",
            "static", "inference", "onnx", "utils", "sysconfig", "hub",
            "cost_model", "dataset", "reader", "observability",
            "resilience"}
    if name in lazy:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi import Model

        globals()["Model"] = Model
        return Model
    if name in ("summary", "flops"):
        from .hapi.summary import flops, summary

        globals().update(summary=summary, flops=flops)
        return globals()[name]
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")

from .framework.io_utils import load, save  # noqa: F401


def enable_static():
    """Switch to static-graph (program-building) mode (paddle.enable_static)."""
    _flags.set_static_mode(True)


def disable_static(place=None):
    _flags.set_static_mode(False)


def in_dynamic_mode():
    return not _flags.in_static_mode()


class _NoGrad:
    """paddle.no_grad: usable as context manager and decorator."""

    def __call__(self, fn=None):
        if fn is None:
            return _flags.no_grad_guard()
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _flags.no_grad_guard():
                return fn(*a, **kw)

        return wrapper

    def __enter__(self):
        self._cm = _flags.no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


no_grad = _NoGrad()
enable_grad = _flags.enable_grad_guard


def is_grad_enabled():
    return _flags.is_grad_enabled()


def set_grad_enabled(mode):
    return _flags.set_grad_enabled(mode)


def get_device():
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def set_device(device):
    return device


def device_count():
    import jax

    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def get_cudnn_version():
    return None


def _metadata_dtype(dtype):
    # metadata queries report the dtype ASKED about — no x64 demotion
    # (that demotion is intentional only for tensor creation)
    if isinstance(dtype, str):
        return dtype
    name = getattr(dtype, "name", None) or str(dtype)
    return name.replace("paddle.", "").replace("jax.numpy.", "")


def iinfo(dtype):
    import numpy as _np

    return _np.iinfo(_np.dtype(_metadata_dtype(dtype)))


def finfo(dtype):
    import jax.numpy as _jnp
    import numpy as _np

    name = _metadata_dtype(dtype)
    if name == "bfloat16":
        return _jnp.finfo(_jnp.bfloat16)
    return _np.finfo(_np.dtype(name))


class CPUPlace:
    def __repr__(self):
        return "Place(cpu)"


class TPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(tpu:{self.device_id})"


class CUDAPlace(TPUPlace):
    """Accepted for ported code; maps to the accelerator (TPU) place."""


class CUDAPinnedPlace(CPUPlace):
    pass


class version:
    """paddle.version parity surface."""

    full_version = __version__
    major, minor, patch = (__version__.split(".") + ["0", "0"])[:3]
    rc = "0"
    cuda_version = "False"
    cudnn_version = "False"
    tpu = True

    @staticmethod
    def show():
        print(f"paddle_tpu {__version__} (XLA/StableHLO/Pallas backend)")

    @staticmethod
    def cuda():
        return "False"

    @staticmethod
    def cudnn():
        return "False"


def is_compiled_with_rocm():
    return False


def is_compiled_with_tpu():
    return True


def synchronize():
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def tensor_method_grad_fix():  # pragma: no cover
    pass


# ---- top-level surface completion (reference python/paddle/__init__.py) ----
import jax.numpy as _jnp  # noqa: E402
from .core import dtypes as _dtypes  # noqa: E402
from .nn import ParamAttr  # noqa: F401,E402
from .distributed.parallel import DataParallel  # noqa: F401,E402

dtype = _jnp.dtype
bool = _dtypes.convert_dtype("bool")  # paddle.bool dtype alias  # noqa: A001


def get_cuda_rng_state():
    """CUDA-namespace RNG parity: returns the framework generator state."""
    from .core import rng as _rng

    return [_rng.default_generator.get_state()]


def set_cuda_rng_state(state):
    from .core import rng as _rng

    _rng.default_generator.set_state(state[0] if isinstance(state, (list,
                                     tuple)) else state)


class LazyGuard:
    """Reference LazyGuard delays parameter materialization; jax arrays
    are cheap eagerly, so the guard is a no-op context (documented)."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def randint_like(x, low=0, high=None, dtype=None, name=None):
    shape = list(x.shape)
    return randint(low, high, shape=shape,
                   dtype=dtype or str(x.dtype))


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (paddle.batch)."""
    def _gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return _gen


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of rows (upper triangle, flat)."""
    from .core.dispatch import apply as _apply

    def f(v):
        n = v.shape[0]
        d = v[:, None, :] - v[None, :, :]
        if p == 2.0:
            m = _jnp.sqrt(_jnp.sum(d * d, axis=-1) + 1e-30)
        else:
            m = _jnp.sum(_jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        iu = _jnp.triu_indices(n, k=1)
        return m[iu]

    return _apply("pdist", f, x)


def column_stack(x, name=None):
    from . import ops as _ops

    cols = [t.reshape([-1, 1]) if len(t.shape) == 1 else t for t in x]
    return _ops.concat(cols, axis=1)


def row_stack(x, name=None):
    from . import ops as _ops

    return _ops.vstack(x)


def unfold(x, axis, size, step, name=None):
    """Sliding windows over `axis` (paddle.unfold tensor op — distinct
    from nn.functional.unfold's im2col)."""
    from .core.dispatch import apply as _apply

    def f(v):
        length = v.shape[axis]
        n_win = (length - size) // step + 1
        idx = _jnp.arange(n_win)[:, None] * step + _jnp.arange(size)
        taken = _jnp.take(v, idx.reshape(-1), axis=axis)
        shp = list(v.shape)
        new = shp[:axis] + [n_win, size] + shp[axis + 1:]
        out = taken.reshape(new)
        # paddle puts the window dim LAST
        return _jnp.moveaxis(out, axis + 1, -1)

    return _apply("unfold", f, x)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: the jax runtime installs no signal handlers to disable."""


def check_shape(x):
    return list(x.shape)


# inplace twins missing from the generated set
def expm1_(x, name=None):
    from . import ops as _ops

    return x._rebind(_ops.expm1(x))


def square_(x, name=None):
    from . import ops as _ops

    return x._rebind(_ops.square(x))


def erf_(x, name=None):
    from . import ops as _ops

    return x._rebind(_ops.erf(x))
