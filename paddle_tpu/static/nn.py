"""static.nn: program-building layer helpers.

Role parity: `paddle.static.nn` (`python/paddle/static/nn/common.py` fc,
conv2d, batch_norm, embedding ...). Each helper instantiates the eager layer
(parameters materialize immediately — inline startup semantics) and calls it
on the symbolic Variable so the forward records into the Program.
"""
from __future__ import annotations

import numpy as np


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    from .. import nn, ops

    decl = getattr(x, "declared_shape", None) or x.shape
    if any(d == -1 for d in decl[num_flatten_dims:]):
        raise ValueError("fc: flattened dims must be static")
    in_dim = int(np.prod(decl[num_flatten_dims:]))
    layer = nn.Linear(in_dim, size)
    flat = x
    if len(decl) > num_flatten_dims + 1:
        flat = ops.reshape(x, [-1, in_dim])
    out = layer(flat)
    if activation is not None:
        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, dtype="float32",
              param_attr=None):
    from .. import nn

    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW"):
    from .. import nn

    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      data_format=data_format)
    out = layer(input)
    if act is not None:
        out = getattr(nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               data_layout="NCHW", **kwargs):
    from .. import nn

    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                           data_format=data_layout)
    out = layer(input)
    if act is not None:
        out = getattr(nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, **kwargs):
    from .. import nn

    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = nn.LayerNorm(shape, epsilon=epsilon)
    return layer(input)


# ---- reference static.nn __all__ completion ----

def _act(out, act):
    if act:
        from .. import nn

        return getattr(nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from .. import nn

    cin = input.shape[1]
    layer = nn.Conv2DTranspose(cin, num_filters, filter_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups)
    return _act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from .. import nn

    layer = nn.Conv3D(input.shape[1], num_filters, filter_size,
                      stride=stride, padding=padding, dilation=dilation,
                      groups=groups)
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from .. import nn

    layer = nn.Conv3DTranspose(input.shape[1], num_filters, filter_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups)
    return _act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn

    layer = nn.GroupNorm(groups, input.shape[1], epsilon=epsilon)
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn

    return nn.InstanceNorm2D(input.shape[1], epsilon=epsilon)(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Feature-wise standardization by running statistics (reference
    data_norm): per-feature (x - mean) / sqrt(var) without batch
    coupling."""
    from ..core.dispatch import apply
    import jax.numpy as jnp

    def f(x):
        mu = jnp.mean(x, axis=0, keepdims=True)
        var = jnp.var(x, axis=0, keepdims=True)
        return (x - mu) / jnp.sqrt(var + epsilon)

    return _act(apply("data_norm", f, input), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn

    n = 1 if mode == "all" else x.shape[1]
    layer = nn.PReLU(num_parameters=n)
    return layer(x)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from .. import vision

    import paddle_tpu as P

    cin = x.shape[1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    weight = P.create_parameter([num_filters, cin // groups, ks[0], ks[1]],
                                "float32")
    return vision.ops.deform_conv2d(x, offset, weight, mask=mask,
                                    stride=stride, padding=padding,
                                    dilation=dilation,
                                    deformable_groups=deformable_groups,
                                    groups=groups)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import nn

    layer = nn.Bilinear(x.shape[-1], y.shape[-1], size)
    return _act(layer(x, y), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized view of a weight Variable (reference
    static.nn.spectral_norm)."""
    from ..core.dispatch import apply
    import jax.numpy as jnp

    def f(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1) \
            .astype(jnp.float32)
        u = jnp.ones((mat.shape[0],), jnp.float32)
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ (mat @ v)
        return (w.astype(jnp.float32) / jnp.maximum(sigma, eps)) \
            .astype(w.dtype)

    return apply("spectral_norm", f, weight)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference row_conv): each timestep
    mixes the next `future_context_size` steps with learned weights."""
    import paddle_tpu as P
    from ..core.dispatch import apply
    import jax.numpy as jnp

    d = input.shape[-1]
    w = P.create_parameter([future_context_size + 1, d], "float32")

    def f(x, wv):
        outs = []
        t = x.shape[1]
        for k in range(future_context_size + 1):
            shifted = jnp.pad(x[:, k:], ((0, 0), (0, k), (0, 0)))
            outs.append(shifted * wv[k])
        return sum(outs[1:], outs[0])

    return _act(apply("row_conv", f, input, w), act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce): true-class +
    uniformly sampled negatives, BCE in one pass."""
    import paddle_tpu as P
    from ..core.dispatch import apply
    import jax
    import jax.numpy as jnp

    d = input.shape[-1]
    w = P.create_parameter([num_total_classes, d], "float32")
    b = P.create_parameter([num_total_classes], "float32", is_bias=True)
    key = jax.random.PRNGKey(seed)

    def f(x, y, wv, bv):
        n = x.shape[0]
        neg = jax.random.randint(key, (n, num_neg_samples), 0,
                                 num_total_classes)
        yy = y.reshape(-1, 1).astype(jnp.int32)
        cls = jnp.concatenate([yy, neg], axis=1)        # [N, 1+K]
        wc = wv[cls]                                    # [N, 1+K, D]
        logits = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                            wc.astype(jnp.float32)) + bv[cls]
        tgt = jnp.concatenate(
            [jnp.ones((n, 1)), jnp.zeros((n, num_neg_samples))], axis=1)
        per = jnp.maximum(logits, 0) - logits * tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(per, axis=1, keepdims=True)

    return apply("nce", f, input, label, w, b)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS sparse-table embedding → dense embedding on TPU (the sharded
    table is the mpu VocabParallelEmbedding under mp)."""
    return embedding(input, size, padding_idx=padding_idx, dtype=dtype)


# control flow (reference static.nn control_flow): thin functional forms
# over the converted-control-flow helpers
def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    from ..jit.dy2static import _tensor_bool

    import paddle_tpu as P
    from ..core import flags as _flags
    from ..core.tensor import Tensor

    if isinstance(pred, Tensor) and _flags.in_trace():
        import jax

        return jax.lax.cond(pred._value.astype(bool).reshape(()),
                            lambda: true_fn(), lambda: false_fn())
    return true_fn() if _tensor_bool(pred) else false_fn()


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        from ..jit.dy2static import _tensor_bool

        if _tensor_bool(pred):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(branch_index.numpy() if hasattr(branch_index, "numpy")
              else branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    raise ValueError(f"switch_case: no branch {idx} and no default")


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Reference while_loop; converts to lax.while_loop under trace via
    the dy2static helper, plain python loop eagerly."""
    from ..jit.dy2static import _jst_while

    names = [f"v{i}" for i in range(len(loop_vars))]
    out = _jst_while(lambda *vs: cond(*vs), lambda *vs: body(*vs),
                     names, tuple(loop_vars))
    return list(out)


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """PyLayer in a program (reference static_pylayer): custom forward
    (+ optional custom backward) recorded as one op."""
    import jax

    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    if backward_fn is None:
        return forward_fn(*inputs)

    @jax.custom_vjp
    def core(*vals):
        out = forward_fn(*[Tensor(v) for v in vals])
        return out._value if isinstance(out, Tensor) else out

    def core_f(*vals):
        return core(*vals), vals

    def core_b(res, g):
        outs = backward_fn(Tensor(g))
        outs = outs if isinstance(outs, (list, tuple)) else (outs,)
        return tuple(o._value if isinstance(o, Tensor) else o
                     for o in outs)

    core.defvjp(core_f, core_b)
    return apply("static_pylayer", core, *inputs)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from . import py_func as _pf  # top-level static.py_func

    return _pf(func, x, out, backward_func)


# LoD sequence ops: the reference operates on LoDTensors, a variable-
# length container this framework deliberately does not have (dense
# [B, S] + lengths/masks replace it; the reference itself deprecates
# LoD). Loud, documented gates with the migration hint.
def _lod_gate(name):
    def g(*a, **kw):
        raise NotImplementedError(
            f"static.nn.{name} operates on LoDTensors, which this build "
            "replaces by dense [batch, seq] tensors + length masks (see "
            "README); express the computation with nn/ops over padded "
            "tensors (e.g. sequence_mask, gather, segment ops)")

    g.__name__ = name
    return g


sequence_conv = _lod_gate("sequence_conv")
sequence_softmax = _lod_gate("sequence_softmax")
sequence_pool = _lod_gate("sequence_pool")
sequence_concat = _lod_gate("sequence_concat")
sequence_first_step = _lod_gate("sequence_first_step")
sequence_last_step = _lod_gate("sequence_last_step")
sequence_slice = _lod_gate("sequence_slice")
sequence_expand = _lod_gate("sequence_expand")
sequence_expand_as = _lod_gate("sequence_expand_as")
sequence_pad = _lod_gate("sequence_pad")
sequence_unpad = _lod_gate("sequence_unpad")
sequence_reshape = _lod_gate("sequence_reshape")
sequence_scatter = _lod_gate("sequence_scatter")
sequence_enumerate = _lod_gate("sequence_enumerate")
sequence_reverse = _lod_gate("sequence_reverse")
