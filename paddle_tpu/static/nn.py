"""static.nn: program-building layer helpers.

Role parity: `paddle.static.nn` (`python/paddle/static/nn/common.py` fc,
conv2d, batch_norm, embedding ...). Each helper instantiates the eager layer
(parameters materialize immediately — inline startup semantics) and calls it
on the symbolic Variable so the forward records into the Program.
"""
from __future__ import annotations

import numpy as np


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    from .. import nn, ops

    decl = getattr(x, "declared_shape", None) or x.shape
    if any(d == -1 for d in decl[num_flatten_dims:]):
        raise ValueError("fc: flattened dims must be static")
    in_dim = int(np.prod(decl[num_flatten_dims:]))
    layer = nn.Linear(in_dim, size)
    flat = x
    if len(decl) > num_flatten_dims + 1:
        flat = ops.reshape(x, [-1, in_dim])
    out = layer(flat)
    if activation is not None:
        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, dtype="float32",
              param_attr=None):
    from .. import nn

    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW"):
    from .. import nn

    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      data_format=data_format)
    out = layer(input)
    if act is not None:
        out = getattr(nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               data_layout="NCHW", **kwargs):
    from .. import nn

    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                           data_format=data_layout)
    out = layer(input)
    if act is not None:
        out = getattr(nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, **kwargs):
    from .. import nn

    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = nn.LayerNorm(shape, epsilon=epsilon)
    return layer(input)
