"""Static-graph backward: append_backward / gradients.

Role parity: `paddle.static.append_backward`
(`python/paddle/base/backward.py`) which appends grad ops per forward op.
TPU-first collapse: one recorded `backward` op marks "differentiate the
prefix graph at this point"; the compiler realizes it as a single `jax.vjp`
over the replayed prefix, so XLA sees exactly the fused fwd+bwd program a
hand-appended grad-op chain would describe.
"""
from __future__ import annotations

import jax
import numpy as np

from .framework import OpRecord, Variable, default_main_program


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Record grads of `loss` w.r.t. trainable captured parameters.

    Returns list of (param, grad_variable) pairs, as the reference does.
    """
    prog = default_main_program()
    if not isinstance(loss, Variable) or loss.program is not prog:
        raise ValueError("append_backward needs a loss Variable of the "
                         "default main program")
    if prog._has_backward:
        raise RuntimeError("append_backward already called on this Program")

    if parameter_list is None:
        params = [p for p in prog.all_parameters()
                  if not p.stop_gradient and getattr(p, "trainable", True)]
    else:
        params = list(parameter_list)
    if no_grad_set:
        drop = set(id(p) for p in no_grad_set)
        params = [p for p in params if id(p) not in drop]
    if not params:
        raise ValueError("no trainable parameters captured by the program")

    wrt_caps = [prog.capture(p) for p in params]
    pairs = []
    grad_vids = []
    for p, cap in zip(params, wrt_caps):
        aval = jax.ShapeDtypeStruct(tuple(p._value.shape),
                                    np.dtype(p._value.dtype))
        g = Variable(aval, name=f"{p.name or 'param'}@GRAD", program=prog)
        prog.register_var(g)
        grad_vids.append(g.vid)
        pairs.append((p, g))

    prog.ops.append(OpRecord(
        "backward", "append_backward",
        out_vids=grad_vids,
        extra={"loss_vid": loss.vid, "wrt_caps": wrt_caps}))
    prog._has_backward = True
    prog._bump()
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients parity: d(sum of targets)/d(inputs) where
    inputs are captured eager tensors (parameters/constants)."""
    if target_gradients is not None:
        raise NotImplementedError(
            "target_gradients is not supported; pre-scale the targets")
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    from .. import ops

    loss = ops.sum(targets[0])
    for t in targets[1:]:
        loss = ops.add(loss, ops.sum(t))
    pairs = append_backward(loss, parameter_list=list(inputs),
                            no_grad_set=no_grad_set)
    return [g for _, g in pairs]
