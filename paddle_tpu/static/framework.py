"""Static-graph program representation.

Role parity: `Program`/`Block`/`Variable` of the reference
(`paddle/fluid/framework/program_desc.h`, `python/paddle/base/framework.py`)
and the PIR program it translates to (`paddle/pir/`, SURVEY §2.4).

TPU-first collapse: a Program is a recorded DAG of pure-op applications over
symbolic `Variable`s. Shape/dtype inference at build time is `jax.eval_shape`
(the InferMeta analog); there is no separate serialization IR — compilation
lowers the recorded ops straight through `jax.jit` to StableHLO/XLA, and
`save_inference_model` serializes via `jax.export` (the ProgramDesc analog).
Parameters materialize eagerly at creation (the startup program is an API
no-op), held as scope-bound captures so optimizer writebacks persist across
`Executor.run` calls without recompiling.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

from ..core import dtypes as _dtypes
from ..core.tensor import Tensor


class Variable(Tensor):
    """Symbolic tensor in a Program (build-time handle, no device value).

    `_value` holds a `jax.ShapeDtypeStruct`, so shape/dtype properties and
    `jnp.issubdtype` checks in the dispatch gate work unchanged; any attempt
    to read data eagerly fails loudly.
    """

    __slots__ = ("vid", "program", "is_data", "declared_shape")

    def __init__(self, aval, name=None, program=None, stop_gradient=True):
        # bypass Tensor.__init__'s asarray path: bind the abstract value
        self._value = aval
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._hooks = []
        self.name = name
        self.persistable = False
        self.dist_attr = None
        self.program = program
        self.is_data = False
        self.declared_shape = None
        self.vid = program._next_vid() if program is not None else -1

    @property
    def shape(self):
        # surface -1 for symbolic (batch) dims like the reference: the aval
        # binds a placeholder 1 so tracing works, but letting user code read
        # that 1 as a concrete batch size would bake it into the program
        if self.declared_shape is not None:
            return list(self.declared_shape)
        return list(self._value.shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable {self.name!r} is symbolic (static mode); run it "
            "through Executor.run(fetch_list=[...]) to get a value")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self._value.dtype})")


class OpRecord:
    """One recorded op application.

    kind: 'compute' (pure fn replay), 'backward' (vjp over the prefix graph),
    'update' (optimizer step with scope writebacks).
    """

    __slots__ = ("kind", "name", "fn", "leafspec", "treedef", "out_vids",
                 "out_tree", "extra")

    def __init__(self, kind, name, fn=None, leafspec=(), treedef=None,
                 out_vids=(), out_tree=None, extra=None):
        self.kind = kind
        self.name = name
        self.fn = fn
        self.leafspec = list(leafspec)
        self.treedef = treedef
        self.out_vids = list(out_vids)
        self.out_tree = out_tree
        self.extra = extra or {}


class Program:
    """Recorded op list + captured eager tensors + mutable scope state."""

    def __init__(self):
        self.ops = []
        self.captures = []          # eager Tensor handles (params, consts)
        self._capture_ids = {}      # id(tensor) -> capture index
        self.scope = {}             # str -> jax array (optimizer slots, step)
        self.feed_vars = {}         # name -> Variable
        self.vars = {}              # vid -> Variable (weak by design: small)
        self._vid = 0
        self._version = 0
        self._has_backward = False
        self.lr_providers = []      # callables evaluated at run time
        self.random_seed = None

    def _next_vid(self):
        self._vid += 1
        return self._vid

    def _bump(self):
        self._version += 1

    def capture(self, tensor):
        idx = self._capture_ids.get(id(tensor))
        if idx is None:
            idx = len(self.captures)
            self.captures.append(tensor)
            self._capture_ids[id(tensor)] = idx
        return idx

    def register_var(self, var):
        self.vars[var.vid] = var
        return var

    def all_parameters(self):
        from ..core.tensor import Parameter

        return [t for t in self.captures if isinstance(t, Parameter)]

    def list_vars(self):
        return list(self.vars.values())

    def block(self, i=0):
        return self

    def global_block(self):
        return self

    def clone(self, for_test=False):
        # the recorded graph is already side-effect-free; a test clone simply
        # shares ops (dropout keys are threaded per-run, eval determinism is
        # the caller's Layer.eval() responsibility, as in dygraph)
        return self

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, captures={len(self.captures)},"
                f" feeds={list(self.feed_vars)})")


class _Defaults(threading.local):
    def __init__(self):
        self.main = Program()
        self.startup = Program()


_defaults = _Defaults()


def default_main_program():
    return _defaults.main


def default_startup_program():
    return _defaults.startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main, old_startup = _defaults.main, _defaults.startup
    _defaults.main = main_program
    if startup_program is not None:
        _defaults.startup = startup_program
    try:
        yield
    finally:
        _defaults.main = old_main
        _defaults.startup = old_startup


def reset_default_programs():
    _defaults.main = Program()
    _defaults.startup = Program()


def data(name, shape, dtype=None, lod_level=0):
    """Declare a feed Variable (parity: paddle.static.data)."""
    prog = default_main_program()
    dtype = _dtypes.convert_dtype(dtype) or _dtypes.get_default_dtype()
    shape = [(-1 if s is None else int(s)) for s in shape]
    aval = jax.ShapeDtypeStruct(
        tuple(1 if s == -1 else s for s in shape), np.dtype(dtype))
    var = Variable(aval, name=name, program=prog, stop_gradient=True)
    var.is_data = True
    # user-facing shape keeps -1 for the batch dim; compile re-derives real
    # shapes from the fed arrays
    var.declared_shape = shape
    prog.feed_vars[name] = var
    prog.register_var(var)
    prog._bump()
    return var


class InputSpec:
    """Shape/dtype spec for jit.save / static feeds (parity:
    `paddle.static.InputSpec`)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(1 if (s is None or s == -1) else int(s)
                           for s in shape)
        self.declared_shape = [(-1 if s is None else int(s)) for s in shape]
        self.dtype = np.dtype(_dtypes.convert_dtype(dtype) or "float32")
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.declared_shape}, dtype={self.dtype},"
                f" name={self.name})")
