"""Static-mode op recording: the append_op analog.

Role parity: `Block.append_op` + InferMeta invocation of the reference
(`python/paddle/base/framework.py`, `paddle/phi/infermeta/`). Under
`paddle.enable_static()`, the dispatch gate routes every op whose inputs
contain a symbolic `Variable` here instead of executing it; ops over purely
eager tensors (parameter initializers) still run immediately — the inline
startup-program semantics.
"""
from __future__ import annotations

import jax
import numpy as np

from ..core.tensor import Tensor
from .framework import OpRecord, Variable, default_main_program


def _is_tensor(x):
    return isinstance(x, Tensor)


def should_record(args, kwargs):
    leaves, _ = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    return any(isinstance(l, Variable) for l in leaves)


def record(name, fn, args, kwargs):
    """Append one compute op to the default main program; return symbolic
    output Variables with shapes from `jax.eval_shape` (InferMeta)."""
    prog = default_main_program()
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_is_tensor)

    leafspec = []
    abstract = []
    any_grad_input = False
    for l in leaves:
        if isinstance(l, Variable):
            if l.program is not None and l.program is not prog:
                raise ValueError(
                    f"op {name!r} mixes Variables from different Programs")
            leafspec.append(("var", l.vid))
            abstract.append(l._value)
            if not l.stop_gradient:
                any_grad_input = True
        elif isinstance(l, Tensor):
            idx = prog.capture(l)
            leafspec.append(("cap", idx))
            abstract.append(
                jax.ShapeDtypeStruct(tuple(l._value.shape), l._value.dtype))
            if not l.stop_gradient:
                any_grad_input = True
        else:
            leafspec.append(("py", l))
            abstract.append(l)

    dyn_idx = [i for i, spec in enumerate(leafspec) if spec[0] != "py"]

    def abstract_call(*dyn_vals):
        cur = list(abstract)
        for i, v in zip(dyn_idx, dyn_vals):
            cur[i] = v
        a, kw = jax.tree_util.tree_unflatten(treedef, cur)
        return fn(*a, **kw)

    # ops that draw randomness split the global generator key inside their
    # body; eval_shape traces that as an abstract split — restore the
    # concrete key afterwards so no tracer leaks into the generator (the
    # compiled replay threads the real key per run)
    from ..core import rng

    old_key = rng.default_generator.get_state()
    try:
        out_shapes = jax.eval_shape(
            abstract_call, *[abstract[i] for i in dyn_idx])
    finally:
        rng.default_generator.set_state(old_key)
    out_leaves, out_tree = jax.tree_util.tree_flatten(out_shapes)

    out_vars = []
    for i, aval in enumerate(out_leaves):
        sg = not (any_grad_input
                  and np.issubdtype(np.dtype(aval.dtype), np.inexact))
        v = Variable(aval, name=f"{name}_{prog._vid + 1}.out{i}",
                     program=prog, stop_gradient=sg)
        prog.register_var(v)
        out_vars.append(v)

    prog.ops.append(OpRecord(
        "compute", name, fn=fn, leafspec=leafspec, treedef=treedef,
        out_vids=[v.vid for v in out_vars], out_tree=out_tree))
    prog._bump()
    return jax.tree_util.tree_unflatten(out_tree, out_vars)
