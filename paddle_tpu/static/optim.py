"""Static-mode optimizer.minimize: record backward + update ops.

Role parity: `Optimizer.minimize` appending backward + optimizer ops to the
Program (`python/paddle/optimizer/optimizer.py` static branch). The recorded
update op reuses the optimizer's pure `update()` rule — the same single
source of truth the eager `.step()` and the sharded functional path use — so
the whole train step compiles to one XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from .backward import append_backward
from .framework import OpRecord, default_main_program


def minimize_static(opt, loss, parameters=None, no_grad_set=None):
    prog = default_main_program()
    if parameters is None:
        parameters = opt._parameter_list
    if parameters is None:
        parameters = [p for p in prog.all_parameters()
                      if not p.stop_gradient and getattr(p, "trainable", True)]
    params_grads = append_backward(loss, parameter_list=parameters,
                                   no_grad_set=no_grad_set)

    items = []
    slot_names = {}
    for p, g in params_grads:
        ci = prog.capture(p)
        slots = opt.init_slots(p._value)
        names = sorted(slots)
        slot_names[ci] = names
        for k in names:
            prog.scope.setdefault(f"opt::{ci}::{k}", slots[k])
        if opt._multi_precision and p._value.dtype != jnp.float32:
            prog.scope.setdefault(f"opt::{ci}::@master",
                                  p._value.astype(jnp.float32))
        lrm = p.optimize_attr.get("learning_rate", 1.0) \
            if hasattr(p, "optimize_attr") else 1.0
        items.append((ci, g.vid, opt._wd_for(p), float(lrm)))

    prog.scope.setdefault("@opt_step", jnp.zeros((), jnp.int32))
    lr_slot = len(prog.lr_providers)
    prog.lr_providers.append(opt.get_lr)

    prog.ops.append(OpRecord(
        "update", type(opt).__name__,
        extra={"optimizer": opt, "items": items, "slot_names": slot_names,
               "lr_slot": lr_slot}))
    prog._bump()
    return [], params_grads
