"""paddle_tpu.static: static-graph (program-building) API.

Role parity: `paddle.static` (`python/paddle/static/`, SURVEY §2.6) over the
executors of §2.4. The reference path Program→PIR→PirInterpreter collapses
on TPU to: record pure ops on symbolic Variables (framework.py), infer
shapes via jax.eval_shape, compile the whole program with jax.jit
(executor.py), serialize via jax.export (io.py).

Design rule: only ops with at least one symbolic Variable input record into
the Program; ops over eager tensors alone (parameter initializers, constant
folding) execute immediately — inline startup-program semantics. To put a
parameter-only expression in the graph, route it through a Variable (e.g.
multiply by a fed constant) or compute it inside a layer forward.
"""
from __future__ import annotations

import contextlib

from .framework import (  # noqa: F401
    InputSpec, Program, Variable, data, default_main_program,
    default_startup_program, program_guard, reset_default_programs,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import Executor, global_scope, scope_guard  # noqa: F401
from .io import (  # noqa: F401
    load_inference_model, save_inference_model,
)
from . import nn  # noqa: F401
from .optim import minimize_static  # noqa: F401


def CompiledProgram(program, build_strategy=None):
    """Every Program already compiles whole-graph via XLA; identity shim."""
    return program


class BuildStrategy:
    """No-op strategy carrier (XLA owns fusion/memory decisions)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


# ---- reference __all__ completion (python/paddle/static/__init__.py) ----

def save(program, model_path, protocol=4, **configs):
    """Persist a Program's parameters + scope (reference static.save)."""
    import pickle

    state = {"params": {(getattr(p, "name", None) or f"p{i}"): _np_of(p)
                        for i, p in enumerate(program.all_parameters())},
             "scope": {k: _np_of(v) for k, v in program.scope.items()}}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    return model_path + ".pdparams"


def _np_of(v):
    import numpy as np

    return np.asarray(v._value if hasattr(v, "_value") else v)


def load(program, model_path, executor=None, var_list=None):
    """Reload static.save output into the program (reference static.load)."""
    import pickle

    import jax.numpy as jnp

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for i, p in enumerate(program.all_parameters()):
        name = getattr(p, "name", None) or f"p{i}"
        if name in state["params"]:
            p.set_value(state["params"][name])
    for k, v in state.get("scope", {}).items():
        program.scope[k] = jnp.asarray(v)
    return program


def serialize_program(feed_vars, fetch_vars, **kwargs):
    """Serialized bytes of the captured program structure (reference
    serialize_program's pb bytes role): pickled op-list metadata."""
    import pickle

    prog = default_main_program()
    meta = {"n_ops": len(prog.ops),
            "feeds": [getattr(v, "name", None) for v in feed_vars],
            "fetches": [getattr(v, "name", None) for v in fetch_vars]}
    return pickle.dumps(meta, protocol=4)


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    import pickle

    prog = default_main_program()
    return pickle.dumps({(getattr(p, "name", None) or f"p{i}"): _np_of(p)
                         for i, p in enumerate(prog.all_parameters())},
                        protocol=4)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    import pickle

    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    import pickle

    state = pickle.loads(data)
    for i, p in enumerate(program.all_parameters()):
        name = getattr(p, "name", None) or f"p{i}"
        if name in state:
            p.set_value(state[name])
    return program


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference prunes/cleans the program for inference; the recorded
    program is already minimal (pure-op list) — identity."""
    return program


def load_program_state(model_path, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)["params"]


def set_program_state(program, state_dict):
    for i, p in enumerate(program.all_parameters()):
        name = getattr(p, "name", None) or f"p{i}"
        if name in state_dict:
            p.set_value(state_dict[name])
    return program


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import paddle_tpu as P

    t = P.full(shape, value, dtype=dtype)
    t.persistable = persistable
    if name:
        t.name = name
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import paddle_tpu as P

    return P.create_parameter(shape, dtype, name=name, attr=attr,
                              is_bias=is_bias,
                              default_initializer=default_initializer)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import paddle_tpu as P

    return P.accuracy(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    import paddle_tpu as P

    return P.auc(input, label, curve=curve, num_thresholds=num_thresholds)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=False,
          print_tensor_lod=False, print_phase="both"):
    """Debug print inside a program (reference static.Print): routes
    through jax.debug.print so it fires from compiled executions too."""
    import jax

    def f(v):
        jax.debug.print((message or "") + "{x}", x=v)
        return v

    from ..core.dispatch import apply

    return apply("print", f, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside a program (reference static.py_func):
    pure_callback keeps it runnable under jit; optional custom backward."""
    import jax
    import numpy as np

    from ..core.dispatch import apply

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(str(o.dtype)))
             for o in outs]

    def f(*vals):
        res = jax.pure_callback(
            lambda *a: func(*a), specs if len(specs) > 1 else specs[0],
            *vals)
        return res

    return apply("py_func", f, *xs)


class WeightNormParamAttr:
    """ParamAttr marker requesting weight_norm reparametrization
    (reference WeightNormParamAttr); consumed by nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.trainable = trainable


class ExponentialMovingAverage:
    """EMA over trainable parameters (reference static.
    ExponentialMovingAverage): update() folds current weights in;
    apply()/restore() swap averaged weights for evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._ema = {}
        self._backup = None
        self._params = None
        self._step = 0

    def _param_list(self):
        if self._params is None:
            prog = default_main_program()
            self._params = list(prog.all_parameters())
        return self._params

    def update(self):
        import jax.numpy as jnp

        self._step += 1
        d = min(self.decay, (1 + self._step) / (10 + self._step))
        for i, p in enumerate(self._param_list()):
            cur = p._value.astype(jnp.float32)
            prev = self._ema.get(i, cur)
            self._ema[i] = d * prev + (1 - d) * cur

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp

        self._backup = [jnp.asarray(p._value) for p in self._param_list()]
        for i, p in enumerate(self._param_list()):
            if i in self._ema:
                p._value = self._ema[i].astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._param_list(), self._backup):
            p._value = b
        self._backup = None


# IPU tier: third-vendor hardware this build does not target (PJRT is
# the backend ABI here) — loud, documented gates.
def _ipu_gate(name):
    def g(*a, **kw):
        raise NotImplementedError(
            f"{name} targets Graphcore IPU hardware; this build's device "
            "tier is PJRT/TPU (see README Scope notes)")

    g.__name__ = name
    return g


ipu_shard_guard = _ipu_gate("ipu_shard_guard")
IpuCompiledProgram = _ipu_gate("IpuCompiledProgram")
IpuStrategy = _ipu_gate("IpuStrategy")
set_ipu_shard = _ipu_gate("set_ipu_shard")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server stack, "
        "excluded by design (README Scope notes)")
