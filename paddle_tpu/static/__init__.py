"""paddle_tpu.static: static-graph (program-building) API.

Role parity: `paddle.static` (`python/paddle/static/`, SURVEY §2.6) over the
executors of §2.4. The reference path Program→PIR→PirInterpreter collapses
on TPU to: record pure ops on symbolic Variables (framework.py), infer
shapes via jax.eval_shape, compile the whole program with jax.jit
(executor.py), serialize via jax.export (io.py).

Design rule: only ops with at least one symbolic Variable input record into
the Program; ops over eager tensors alone (parameter initializers, constant
folding) execute immediately — inline startup-program semantics. To put a
parameter-only expression in the graph, route it through a Variable (e.g.
multiply by a fed constant) or compute it inside a layer forward.
"""
from __future__ import annotations

import contextlib

from .framework import (  # noqa: F401
    InputSpec, Program, Variable, data, default_main_program,
    default_startup_program, program_guard, reset_default_programs,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import Executor, global_scope, scope_guard  # noqa: F401
from .io import (  # noqa: F401
    load_inference_model, save_inference_model,
)
from . import nn  # noqa: F401
from .optim import minimize_static  # noqa: F401


def CompiledProgram(program, build_strategy=None):
    """Every Program already compiles whole-graph via XLA; identity shim."""
    return program


class BuildStrategy:
    """No-op strategy carrier (XLA owns fusion/memory decisions)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []
