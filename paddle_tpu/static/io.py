"""Inference-model serialization for static programs.

Role parity: `paddle.static.save/load_inference_model`
(`python/paddle/static/io.py`) which freeze a pruned ProgramDesc + params.
TPU-first: the pruned program is AOT-lowered through `jax.export` to
serialized StableHLO (`.pdmodel`); parameters ship separately (`.pdiparams`)
and are bound at load as executable arguments — the zero-copy deployment
path `AnalysisPredictor` provides in the reference (SURVEY §2.4 inference).
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.export_compat import get_jax_export
from ..core.tensor import Tensor
from .executor import _build
from .framework import default_main_program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    je = get_jax_export()  # raises ExportUnavailableError up front
    program = program or default_main_program()
    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    feed_names = [v.name for v in feed_vars]
    fetch_vids = [v.vid for v in fetch_vars]

    # prune to the forward subgraph reaching the fetches (the reference
    # prunes the ProgramDesc the same way before freezing): training-only
    # ops (backward/update) and unrelated feeds drop out
    import copy

    from .executor import _backward_reach

    keep, needed = _backward_reach(program.ops, fetch_vids,
                                   include_noncompute=False)
    pruned = copy.copy(program)
    pruned.ops = keep
    unresolved = needed - {v.vid for v in feed_vars} \
        - {vid for op in pruned.ops for vid in op.out_vids}
    if unresolved:
        raise ValueError(
            "fetch_vars depend on non-forward values (grads/updates?); "
            f"unresolved vids: {sorted(unresolved)}")
    fn, _ = _build(pruned, feed_names, fetch_vids, [])

    cap_vals = [c._value for c in program.captures]
    from ..core import rng

    key_val = rng.default_generator.get_state()

    def infer_fn(cap_vals_in, feed_vals_in):
        fetches, _, _, _ = fn(feed_vals_in, cap_vals_in, [], [], key_val)
        return fetches

    # symbolic batch dims: every declared -1 becomes its own export symbol
    scope = je.SymbolicScope()
    feed_avals = []
    has_symbolic = False
    for i, v in enumerate(feed_vars):
        decl = getattr(v, "declared_shape", None) or v.shape
        if any(d == -1 for d in decl):
            has_symbolic = True
            spec = ",".join(f"d{i}_{j}" if d == -1 else str(d)
                            for j, d in enumerate(decl))
            shape = je.symbolic_shape(spec, scope=scope)
        else:
            shape = tuple(decl)
        feed_avals.append(jax.ShapeDtypeStruct(shape, v._value.dtype))
    cap_avals = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in cap_vals]

    try:
        exp = je.export(jax.jit(infer_fn))(cap_avals, feed_avals)
    except Exception as e:
        if not has_symbolic:
            raise
        # fall back to concrete batch=1 when the program isn't shape-poly
        # safe — loudly, since the saved signature narrows
        import warnings

        warnings.warn(
            f"shape-polymorphic export failed ({type(e).__name__}: {e}); "
            "saving with the -1 dims fixed to 1 — the frozen model will "
            "only accept that exact shape", RuntimeWarning)
        feed_avals = [
            jax.ShapeDtypeStruct(
                tuple(1 if d == -1 else d
                      for d in (getattr(v, "declared_shape", None) or v.shape)),
                v._value.dtype)
            for v in feed_vars]
        exp = je.export(jax.jit(infer_fn))(cap_avals, feed_avals)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({
            "format": "static_inference",
            "caps": [np.asarray(c) for c in cap_vals],
            "feed_names": feed_names,
            "fetch_names": [v.name for v in fetch_vars],
        }, f)
    return path_prefix


class _ExportedInferenceProgram:
    """Loaded frozen program: Executor.run(self, feed=...) replays it."""

    def __init__(self, exported, caps, feed_names, fetch_names):
        self.exported = exported
        self.caps = [jnp.asarray(c) for c in caps]
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)

    def _run(self, feed, return_numpy=True):
        vals = []
        for n in self.feed_names:
            if n not in feed:
                raise KeyError(f"missing feed {n!r}")
            v = feed[n]
            if isinstance(v, Tensor):
                v = v._value
            vals.append(jnp.asarray(v))
        out = self.exported.call(self.caps, vals)
        if return_numpy:
            return [np.asarray(o) for o in out]
        return [Tensor(o) for o in out]


def load_inference_model(path_prefix, executor=None, **kwargs):
    je = get_jax_export()
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdmodel", "rb") as f:
        exp = je.deserialize(bytearray(f.read()))
    prog = _ExportedInferenceProgram(
        exp, meta["caps"], meta["feed_names"], meta["fetch_names"])
    return [prog, prog.feed_names, prog.fetch_names]
