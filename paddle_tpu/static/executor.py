"""Static-graph Executor: compile the recorded Program through jax.jit.

Role parity: `paddle.static.Executor` → `StandaloneExecutor` →
`PirInterpreter` (`python/paddle/base/executor.py:1152`,
`paddle/fluid/framework/new_executor/`, SURVEY §3.4). The reference builds an
instruction list with dependency analysis, stream assignment, and an async
workqueue; on TPU the whole recorded program lowers to ONE XLA executable —
dependency analysis, scheduling, fusion, and memory planning are the
compiler's job. The executor's remaining duties are the ones XLA can't do:
feed/fetch marshalling, compile caching per (program version, feed
signature), scope state (optimizer slots) threading, and RNG key threading.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..core.tensor import Tensor
from .framework import (Program, Variable, default_main_program,
                        default_startup_program)


def _replay(op, env, cap_vals):
    leaves = []
    for kind, v in op.leafspec:
        if kind == "var":
            leaves.append(env[v])
        elif kind == "cap":
            leaves.append(cap_vals[v])
        else:
            leaves.append(v)
    a, kw = jax.tree_util.tree_unflatten(op.treedef, leaves)
    out = op.fn(*a, **kw)
    out_leaves = jax.tree_util.tree_flatten(out)[0]
    for vid, val in zip(op.out_vids, out_leaves):
        env[vid] = val


def _apply_grad_clip(clip, grads):
    """Functional realization of the eager ClipGrad* objects for the compiled
    update (parity: `python/paddle/nn/clip.py` semantics)."""
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)

    if clip is None:
        return grads
    if isinstance(clip, ClipGradByGlobalNorm):
        gn_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads)
        gn = jnp.sqrt(gn_sq)
        scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(gn, 1e-12))
        return [(g * scale.astype(g.dtype)) for g in grads]
    if isinstance(clip, ClipGradByNorm):
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            s = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
            out.append(g * s.astype(g.dtype))
        return out
    if isinstance(clip, ClipGradByValue):
        lo = clip.min if clip.min is not None else -clip.max
        return [jnp.clip(g, lo, clip.max) for g in grads]
    return grads


def _backward_reach(ops, seed_vids, include_noncompute=True):
    """THE reverse reachability walk (single source of truth for
    Executor pruning, feed checking, and save_inference_model pruning).

    Returns (kept_ops_in_order, needed_vids). Compute ops are kept iff they
    produce a needed vid; backward/update ops are kept when
    `include_noncompute` (training execution) and dropped otherwise
    (inference freezing)."""
    needed = set(seed_vids)
    kept = []
    for op in reversed(ops):
        if op.kind == "compute":
            if not (set(op.out_vids) & needed):
                continue
        elif not include_noncompute:
            continue
        kept.append(op)
        needed.update(v for k, v in op.leafspec if k == "var")
        if op.kind == "backward":
            needed.add(op.extra["loss_vid"])
        elif op.kind == "update":
            needed.update(gv for _, gv, _, _ in op.extra["items"])
    return list(reversed(kept)), needed


def _build(program, feed_names, fetch_vids, scope_keys):
    """Build the pure whole-program function for jax.jit."""
    ops, _ = _backward_reach(program.ops, fetch_vids)
    bwd_idx = next((i for i, o in enumerate(ops) if o.kind == "backward"),
                   None)
    # statically-known set of captures an update op writes back
    cap_out_idx = sorted({ci for o in ops if o.kind == "update"
                          for ci, _, _, _ in o.extra["items"]})

    def fn(feed_vals, cap_vals, scope_vals, rt_scalars, key):
        env = {}
        scope = dict(zip(scope_keys, scope_vals))
        old_key = rng.default_generator.get_state()
        rng.default_generator.set_state(key)
        try:
            for name, val in zip(feed_names, feed_vals):
                env[program.feed_vars[name].vid] = val

            if bwd_idx is None:
                prefix_end = len(ops)
            else:
                prefix_end = bwd_idx

            if bwd_idx is not None:
                bop = ops[bwd_idx]
                wrt_caps = bop.extra["wrt_caps"]
                loss_vid = bop.extra["loss_vid"]

                def fwd(wrt_vals):
                    env2 = dict(env)
                    cap2 = list(cap_vals)
                    for ci, v in zip(wrt_caps, wrt_vals):
                        cap2[ci] = v
                    for op in ops[:prefix_end]:
                        _replay(op, env2, cap2)
                    return env2[loss_vid], env2

                wrt_vals = [cap_vals[ci] for ci in wrt_caps]
                loss_val, vjp_fn, env_aux = jax.vjp(
                    fwd, wrt_vals, has_aux=True)
                grads = vjp_fn(jnp.ones_like(loss_val))[0]
                env = env_aux
                for vid, g in zip(bop.out_vids, grads):
                    env[vid] = g
                rest = ops[bwd_idx + 1:]
            else:
                for op in ops[:prefix_end]:
                    _replay(op, env, cap_vals)
                rest = []

            cap_out = {}
            for op in rest:
                if op.kind == "compute":
                    _replay(op, env, cap_vals)
                elif op.kind == "update":
                    opt = op.extra["optimizer"]
                    items = op.extra["items"]  # [(cap_idx, grad_vid, wd, lrm)]
                    lr = rt_scalars[op.extra["lr_slot"]]
                    t = scope["@opt_step"] + 1
                    scope["@opt_step"] = t
                    grads = [env[gv] for _, gv, _, _ in items]
                    grads = _apply_grad_clip(opt._grad_clip, grads)
                    for (ci, _, wd, lrm), g in zip(items, grads):
                        p = cap_out.get(ci, cap_vals[ci])
                        slot_names = op.extra["slot_names"][ci]
                        slots = {k: scope[f"opt::{ci}::{k}"]
                                 for k in slot_names}
                        mkey = f"opt::{ci}::@master"
                        base = scope[mkey] if mkey in scope \
                            else p.astype(jnp.float32)
                        new_p, new_slots = opt.update(
                            base, g.astype(jnp.float32), slots,
                            lr * lrm, t, wd)
                        cap_out[ci] = new_p.astype(p.dtype)
                        if mkey in scope:
                            scope[mkey] = new_p
                        for k, v in new_slots.items():
                            scope[f"opt::{ci}::{k}"] = v
            new_key = rng.default_generator.get_state()
        finally:
            rng.default_generator.set_state(old_key)

        fetches = [env[v] for v in fetch_vids]
        scope_out = [scope[k] for k in scope_keys]
        return (fetches, scope_out,
                [cap_out.get(i, cap_vals[i]) for i in cap_out_idx], new_key)

    return fn, cap_out_idx


class Executor:
    """Compile-and-run driver for static Programs."""

    def __init__(self, place=None):
        self.place = place
        # id(program) -> (program_ref, version, {sig: (jitfn, cap_out_idx)});
        # holding the ref keeps the id valid; stale versions are evicted so
        # rebuilt programs don't pin old executables
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, scope=None):
        from .io import _ExportedInferenceProgram

        if isinstance(program, _ExportedInferenceProgram):
            return program._run(feed or {}, return_numpy=return_numpy)
        if program is None:
            program = default_main_program()
        if program is default_startup_program() or not program.ops:
            return []
        feed = feed or {}
        fetch_list = fetch_list or []

        fetch_vids = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_vids.append(f.vid)
            elif isinstance(f, str):
                match = [v for v in program.vars.values() if v.name == f]
                if not match:
                    raise KeyError(f"fetch target {f!r} not found")
                fetch_vids.append(match[0].vid)
            else:
                raise TypeError(f"bad fetch target: {f!r}")

        feed_names = sorted(feed)
        feed_vals = []
        for n in feed_names:
            v = feed[n]
            if isinstance(v, Tensor):
                v = v._value
            feed_vals.append(jnp.asarray(v))
        missing = set(program.feed_vars) - set(feed_names)
        used_feeds = [n for n in feed_names if n in program.feed_vars]
        if missing:
            # only an error if a fetch/update actually depends on it; XLA
            # would die cryptically, so check eagerly
            needed = _feeds_needed(program, fetch_vids)
            really = missing & needed
            if really:
                raise KeyError(f"feed missing for data vars: {sorted(really)}")
        feed_names = used_feeds
        feed_vals = [feed_vals[i] for i, n in enumerate(sorted(feed))
                     if n in program.feed_vars]

        scope_keys = sorted(program.scope)
        slot = self._cache.get(id(program))
        if slot is None or slot[1] != program._version:
            slot = (program, program._version, {})
            self._cache[id(program)] = slot
        sig = (tuple(feed_names),
               tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals),
               tuple(fetch_vids), tuple(scope_keys))
        entry = slot[2].get(sig)
        if entry is None:
            fn, cap_out_idx = _build(program, feed_names, fetch_vids,
                                     scope_keys)
            entry = (jax.jit(fn), cap_out_idx)
            slot[2][sig] = entry
        jfn, cap_out_idx = entry

        cap_vals = [c._value for c in program.captures]
        scope_vals = [program.scope[k] for k in scope_keys]
        rt_scalars = [jnp.asarray(p(), jnp.float32)
                      for p in program.lr_providers]
        gen_key = rng.default_generator.get_state()

        fetches, scope_out, cap_out_vals, new_key = jfn(
            feed_vals, cap_vals, scope_vals, rt_scalars, gen_key)

        rng.default_generator.set_state(new_key)
        for k, v in zip(scope_keys, scope_out):
            program.scope[k] = v
        for i, v in zip(cap_out_idx, cap_out_vals):
            program.captures[i]._value = v

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def close(self):
        self._cache.clear()


def _feeds_needed(program, fetch_vids):
    """Which feed names can influence fetches or training ops."""
    _, needed_vids = _backward_reach(program.ops, fetch_vids)
    return {n for n, v in program.feed_vars.items() if v.vid in needed_vids}


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def g():
        yield scope

    return g()


def global_scope():
    return default_main_program().scope
