from .gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion, gpt_1p3b,
    gpt_6p7b, gpt_tiny, llama_7b,
)
