from .gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion, gpt_1p3b,
    gpt_6p7b, gpt_tiny,
)
from .gpt import llama_7b as gpt_llama_7b  # noqa: F401 (legacy alias)
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaPretrainingCriterion,
    llama2_70b_shapes, llama_13b, llama_7b, llama_pipe_layers, llama_tiny,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieForPretraining, ErnieModel,
    ErniePretrainingCriterion, ernie_3_0_medium, ernie_base, ernie_tiny,
)
