"""GPT family — the flagship pretrain model (BASELINE configs 2/3: GPT-3
1.3B / 6.7B under DP+sharding / TP).

Role parity: the reference's Fleet GPT fixture (`test/auto_parallel/
get_gpt_model.py` + PaddleNLP-style mpu usage, SURVEY §3.3). Built from
`distributed.mpu` layers so dp/mp/sep sharding falls out of annotations;
`use_rope=True` + RMSNorm + SwiGLU gives the LLaMA variant (config 4).

TPU-first choices: bf16-friendly module defaults, flash attention via the
Pallas path ([B,S,H,D] layout), `lax`-free python (everything traces into
one XLA program), optional per-block recompute (jax rematerialization).
"""
from __future__ import annotations


import jax.numpy as jnp

from .. import nn
from ..distributed import mpu
from ..distributed.recompute import recompute as _recompute
from ..nn import functional as F
from .generation import GenerationMixin, _static_cache_attention

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt_1p3b", "gpt_6p7b",
           "llama_7b"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, ffn_hidden=None,
                 dropout=0.0, attn_dropout=0.0, use_rope=False,
                 use_rmsnorm=False, use_swiglu=False, tie_embeddings=True,
                 recompute=False, recompute_policy=None,
                 sequence_parallel=False,
                 context_parallel=False, layer_norm_eps=1e-5,
                 fused_head_ce=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.ffn_hidden = ffn_hidden or (
            int(8 * hidden_size / 3 / 128 + 1) * 128 if use_swiglu
            else 4 * hidden_size)
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.use_rope = use_rope
        self.use_rmsnorm = use_rmsnorm
        self.use_swiglu = use_swiglu
        self.tie_embeddings = tie_embeddings
        self.recompute = recompute
        # named remat policy: None/'full' | 'dots' | 'dots_no_batch'
        self.recompute_policy = recompute_policy
        self.sequence_parallel = sequence_parallel
        self.context_parallel = context_parallel
        self.layer_norm_eps = layer_norm_eps
        # training returns hidden states; GPTPretrainingCriterion fuses
        # the LM-head projection into the chunked CE ("cut cross
        # entropy" — the [B,S,V] logits never materialize)
        self.fused_head_ce = fused_head_ce


def _in_trace():
    from ..core import flags

    return flags.in_trace()


def _norm(cfg):
    if cfg.use_rmsnorm:
        return nn.RMSNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
    return nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)


class GPTAttention(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        # fused qkv: column-parallel over heads
        self.qkv_proj = mpu.ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False)
        self.out_proj = mpu.RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True)

    def forward(self, x, cache=None, kv_cache=None, cache_pos=None,
                attn_start=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        if self.cfg.use_rope:
            position_ids = None
            if kv_cache is not None:
                # static-cache path: phases continue from the traced
                # offset; left-padded rows shift so their first REAL
                # token sits at rotary position 0
                from .generation import decode_position_ids

                position_ids = decode_position_ids(cache_pos, b, s,
                                                   attn_start)
            elif cache is not None:
                # legacy concat cache: offset is a host int
                import numpy as _np

                offset = cache[0].shape[1]
                position_ids = _np.arange(offset, offset + s)[None, :].repeat(
                    b, axis=0)
            q, k, _ = F.fused_rotary_position_embedding(
                q, k, None, position_ids=position_ids)
        if kv_cache is not None:
            out, new_cache = _static_cache_attention(
                q, k, v, kv_cache, cache_pos, attn_start)
            out = out.reshape([b, s, h])
            out = self.out_proj(out)
            return out, new_cache
        if cache is not None:
            pk, pv = cache
            from .. import ops

            k = ops.concat([pk, k], axis=1)
            v = ops.concat([pv, v], axis=1)
            cache = (k, v)
        if self.cfg.context_parallel and _in_trace():
            # ring attention over the sep axis (long-context path)
            from ..core.dispatch import apply
            from ..ops.pallas.ring_attention import ring_attention

            out = apply(
                "ring_attention",
                lambda qv, kv, vv: ring_attention(qv, kv, vv, causal=True),
                q, k, v)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.cfg.attn_dropout if self.training else 0.0,
                training=self.training)
        out = out.reshape([b, s, h])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class GPTMLP(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        if cfg.use_swiglu:
            self.gate_up_proj = mpu.ColumnParallelLinear(
                cfg.hidden_size, 2 * cfg.ffn_hidden, gather_output=False)
        else:
            self.up_proj = mpu.ColumnParallelLinear(
                cfg.hidden_size, cfg.ffn_hidden, gather_output=False)
        self.down_proj = mpu.RowParallelLinear(
            cfg.ffn_hidden, cfg.hidden_size, input_is_parallel=True)

    def forward(self, x):
        if self.cfg.use_swiglu:
            x = F.swiglu(self.gate_up_proj(x))
        else:
            x = F.gelu(self.up_proj(x), approximate=True)
        return self.down_proj(x)


class GPTBlock(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.ln_1 = _norm(cfg)
        self.attn = GPTAttention(cfg)
        self.ln_2 = _norm(cfg)
        self.mlp = GPTMLP(cfg)
        self.drop = nn.Dropout(cfg.dropout)

    def _body(self, x):
        if self.cfg.sequence_parallel:
            x = mpu.sequence_parallel_constraint(x)
        x = x + self.drop(self.attn(self.ln_1(x)))
        x = x + self.drop(self.mlp(self.ln_2(x)))
        return x

    def forward(self, x, kv_cache=None, cache_pos=None, attn_start=None):
        if kv_cache is not None:
            a, new_cache = self.attn(self.ln_1(x), kv_cache=kv_cache,
                                     cache_pos=cache_pos,
                                     attn_start=attn_start)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        if self.cfg.recompute and self.training:
            return _recompute(self._body, x,
                              policy=self.cfg.recompute_policy)
        return self._body(x)


class GPTModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.wte = mpu.VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        if not cfg.use_rope:
            self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.h = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = _norm(cfg)

    def forward(self, input_ids, kv_caches=None, cache_pos=None,
                attn_start=None):
        from .. import ops

        x = self.wte(input_ids)
        if not self.cfg.use_rope:
            if kv_caches is not None:
                from .generation import decode_position_ids

                pos = decode_position_ids(
                    cache_pos, input_ids.shape[0], input_ids.shape[1],
                    attn_start)
            else:
                pos = ops.arange(0, input_ids.shape[1], dtype="int32")
            x = x + self.wpe(pos)
        x = self.drop(x)
        if kv_caches is not None:
            new_caches = []
            for block, kc in zip(self.h, kv_caches):
                x, nc = block(x, kv_cache=kc, cache_pos=cache_pos,
                              attn_start=attn_start)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        for block in self.h:
            x = block(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = mpu.ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, has_bias=False)

    def forward(self, input_ids, kv_caches=None, cache_pos=None,
                attn_start=None):
        if kv_caches is not None:
            x, new_caches = self.gpt(input_ids, kv_caches=kv_caches,
                                     cache_pos=cache_pos,
                                     attn_start=attn_start)
        else:
            x = self.gpt(input_ids)
        if self.cfg.fused_head_ce and self.training and kv_caches is None:
            # hidden states out; GPTPretrainingCriterion(model=...) owns
            # the projection (fused with the CE — no [B,S,V] logits).
            # The marker (via the Tensor's name slot) makes a
            # mismatched plain criterion fail loudly instead of treating
            # hidden states as logits.
            x.name = "fused_head_hidden"
            return x
        if self.cfg.tie_embeddings:
            logits = x.matmul(self.gpt.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        if kv_caches is not None:
            return logits, new_caches
        return logits

    def init_kv_caches(self, batch, max_len):
        from .generation import init_kv_caches

        cfg = self.cfg
        dtype = self.gpt.wte.weight.dtype
        return init_kv_caches(cfg.num_layers, batch, cfg.num_heads,
                              cfg.hidden_size // cfg.num_heads, max_len,
                              dtype)


def _ce_fwd_chunk(carry, blk, base, safe_labels, chunk):
    """One online-logsumexp CE step over a [N, chunk] f32 logits block —
    the single source of the running max/sum/picked math for BOTH the
    chunked-softmax CE and the fused linear+CE."""
    m, l, picked = carry
    bm = jnp.max(blk, axis=1)
    m_new = jnp.maximum(m, bm)
    l_new = l * jnp.exp(m - m_new) + \
        jnp.sum(jnp.exp(blk - m_new[:, None]), axis=1)
    in_chunk = (safe_labels >= base) & (safe_labels < base + chunk)
    idx = jnp.clip(safe_labels - base, 0, chunk - 1)
    val = jnp.take_along_axis(blk, idx[:, None], axis=1)[:, 0]
    picked = jnp.where(in_chunk, val, picked)
    return (m_new, l_new, picked)


def _ce_bwd_chunk(blk, base, lse, safe_labels, valid, chunk):
    """d(loss)/d(logits block): softmax recompute minus the one-hot,
    masked to valid tokens — shared by both CE backward scans."""
    p = jnp.exp(blk - lse[:, None])
    idx = safe_labels - base
    onehot = (jnp.arange(chunk)[None, :] == idx[:, None])
    return (p - onehot) * valid[:, None]


def _chunked_softmax_ce(logits, labels, ignore_index, n_chunks=8):
    """Cross entropy over a large vocab without materializing float32
    logits: an online-logsumexp `lax.scan` over vocab chunks (flash-style
    running max/sum) reads the bf16 logits once; the backward recomputes
    the per-chunk softmax and emits d(logits) in the input dtype. Cuts
    the f32 [B*S, V] intermediates (several GB at GPT vocab) out of the
    loss — HBM-bandwidth relief on TPU.

    Returns (total_loss_f32, valid_count_f32) over non-ignored tokens.
    """
    import jax

    n, v = logits.shape
    # pad vocab to a multiple of n_chunks with -inf columns
    chunk = -(-v // n_chunks)
    pad = chunk * n_chunks - v

    def pad_logits(lg):
        if pad:
            return jnp.concatenate(
                [lg, jnp.full((n, pad), -1e30, lg.dtype)], axis=1)
        return lg

    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)

    def fwd_scan(lg):
        lgp = pad_logits(lg).reshape(n, n_chunks, chunk)

        def body(carry, ci):
            blk = lgp[:, ci, :].astype(jnp.float32)
            return _ce_fwd_chunk(carry, blk, ci * chunk, safe_labels,
                                 chunk), None

        init = (jnp.full((n,), -1e30, jnp.float32),
                jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.float32))
        (m, l, picked), _ = jax.lax.scan(body, init,
                                         jnp.arange(n_chunks))
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        per_tok = jnp.where(valid, lse - picked, 0.0)
        return per_tok.sum(), lse

    @jax.custom_vjp
    def core(lg):
        return fwd_scan(lg)[0]

    def core_f(lg):
        total, lse = fwd_scan(lg)
        return total, (lg, lse)

    def core_b(res, g):
        lg, lse = res
        lgp = pad_logits(lg).reshape(n, n_chunks, chunk)

        def body(_, ci):
            blk = lgp[:, ci, :].astype(jnp.float32)
            d = _ce_bwd_chunk(blk, ci * chunk, lse, safe_labels, valid,
                              chunk)
            return None, (g * d).astype(lg.dtype)

        _, dchunks = jax.lax.scan(body, None, jnp.arange(n_chunks))
        dl = jnp.moveaxis(dchunks, 0, 1).reshape(n, n_chunks * chunk)
        return (dl[:, :v],)

    core.defvjp(core_f, core_b)
    return core(logits), valid.astype(jnp.float32).sum()


def _fused_linear_ce(h, w, labels, ignore_index, n_chunks=16):
    """Cross entropy fused WITH the LM-head projection ("cut cross
    entropy"): the [N, V] logits never exist. A `lax.scan` over vocab
    chunks computes `h @ w_chunk.T` on the MXU, folds it into a running
    logsumexp, and picks the target logit; backward recomputes each
    chunk's probabilities and accumulates dh / dW without storing
    activations of size N*V. At GPT-125M bench shape this removes the
    ~3.3 GB bf16 logits (plus their cotangent) from HBM — the largest
    single tensor in the training step.

    h: [N, Hd]; w: [V, Hd] (tied-embedding layout); labels: [N].
    Returns (total_loss_f32, valid_count_f32)."""
    import jax

    n, hd = h.shape
    v = w.shape[0]
    chunk = -(-v // n_chunks)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)

    def chunk_logits(hh, wp, ci):
        # wp: the ONCE-padded weight (pad hoisted out of the scans — a
        # per-iteration pad would re-copy the whole [V, Hd] matrix every
        # chunk in both directions)
        base = ci * chunk
        wc = jax.lax.dynamic_slice_in_dim(wp, base, chunk, axis=0)
        blk = jax.lax.dot_general(
            hh, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [N, chunk]
        col_ok = base + jnp.arange(chunk) < v
        return jnp.where(col_ok[None, :], blk, -1e30), base, wc

    def _padded(ww):
        return jnp.pad(ww, ((0, chunk * n_chunks - v), (0, 0)))

    def fwd_scan(hh, ww):
        wp = _padded(ww)

        def body(carry, ci):
            blk, base, _ = chunk_logits(hh, wp, ci)
            return _ce_fwd_chunk(carry, blk, base, safe_labels,
                                 chunk), None

        init = (jnp.full((n,), -1e30, jnp.float32),
                jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.float32))
        (m, l, picked), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        per_tok = jnp.where(valid, lse - picked, 0.0)
        return per_tok.sum(), lse

    @jax.custom_vjp
    def core(hh, ww):
        return fwd_scan(hh, ww)[0]

    def core_f(hh, ww):
        total, lse = fwd_scan(hh, ww)
        return total, (hh, ww, lse)

    def core_b(res, g):
        # everything differentiable rides the residuals — a custom_vjp
        # bwd closing over outer tracers leaks them out of the linearize
        hh, ww, lse = res
        wp = _padded(ww)

        def body(dh, ci):
            blk, base, wc = chunk_logits(hh, wp, ci)
            d = _ce_bwd_chunk(blk, base, lse, safe_labels, valid,
                              chunk).astype(hh.dtype)          # [N,C]
            dh = dh + jax.lax.dot_general(
                d, wc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dwc = jax.lax.dot_general(
                d, hh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)       # [C, Hd]
            return dh, dwc

        dh, dw_chunks = jax.lax.scan(
            body, jnp.zeros((n, hd), jnp.float32), jnp.arange(n_chunks))
        dw = dw_chunks.reshape(n_chunks * chunk, hd)[:v]
        return ((g * dh).astype(hh.dtype), (g * dw).astype(ww.dtype))

    core.defvjp(core_f, core_b)
    return core(h, w), valid.astype(jnp.float32).sum()


class GPTPretrainingCriterion(nn.Layer):
    """Token-level LM loss with masked mean (parity: the Fleet GPT criterion;
    vocab-parallel CE comes from the logits' mp annotation).

    fused=True (default for large vocabs) uses the chunked online-
    logsumexp CE above; fused=False is the plain F.cross_entropy path.
    Both produce identical values (tested to 1e-5).

    model= (with cfg.fused_head_ce=True on the model): the criterion
    receives HIDDEN states and fuses the LM-head projection into the
    chunked CE (`_fused_linear_ce`) — the [B,S,V] logits and their
    cotangent never exist. Reads the tied embedding weight through the
    live parameter, so the train step's bind_state makes it
    differentiable like any other param."""

    def __init__(self, ignore_index=-100, fused=True, model=None):
        super().__init__()
        self.ignore_index = ignore_index
        self.fused = fused
        self._model = model
        if model is not None:
            assert model.cfg.tie_embeddings, \
                "fused head+CE currently requires tied embeddings"

    def forward(self, logits, labels):
        lv = logits._value if hasattr(logits, "_value") else logits
        yv = labels._value if hasattr(labels, "_value") else labels
        is_hidden = getattr(logits, "name", None) == "fused_head_hidden"
        if is_hidden and (self._model is None or not self.fused):
            # either mismatch silently scores hidden states as logits
            raise RuntimeError(
                "model was built with cfg.fused_head_ce=True (returns "
                "hidden states in training) but the criterion cannot fuse "
                "— construct GPTPretrainingCriterion(model=model) with "
                "fused=True (got model="
                f"{'set' if self._model is not None else 'None'}, "
                f"fused={self.fused})")
        if self._model is not None and self.fused and is_hidden:
            from ..core.dispatch import apply

            w = self._model.gpt.wte.weight  # live (bindable) param

            def f(hh, lb, wv):
                n = 1
                for d in hh.shape[:-1]:
                    n *= d
                total, count = _fused_linear_ce(
                    hh.reshape(n, hh.shape[-1]), wv, lb.reshape(n),
                    self.ignore_index)
                return total / jnp.maximum(count, 1.0)

            return apply("fused_linear_ce", f, logits, labels, w)
        if self.fused and lv.shape[-1] >= 8192:
            from ..core.dispatch import apply

            def f(lg, lb):
                n = 1
                for d in lg.shape[:-1]:
                    n *= d
                total, count = _chunked_softmax_ce(
                    lg.reshape(n, lg.shape[-1]), lb.reshape(n),
                    self.ignore_index)
                return total / jnp.maximum(count, 1.0)

            return apply("fused_softmax_ce", f, logits, labels)
        loss = F.cross_entropy(logits, labels, reduction="mean",
                               ignore_index=self.ignore_index)
        return loss


class GPTEmbeddingStage(nn.Layer):
    """First pipeline stage: token (+position) embedding."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.wte = mpu.VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        if not cfg.use_rope:
            self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, input_ids):
        from .. import ops

        x = self.wte(input_ids)
        if not self.cfg.use_rope:
            pos = ops.arange(0, input_ids.shape[1], dtype="int32")
            x = x + self.wpe(pos)
        return self.drop(x)


class GPTHeadStage(nn.Layer):
    """Last pipeline stage: final norm + LM head."""

    def __init__(self, cfg):
        super().__init__()
        self.ln_f = _norm(cfg)
        self.lm_head = mpu.ColumnParallelLinear(
            cfg.hidden_size, cfg.vocab_size, has_bias=False)

    def forward(self, x):
        return self.lm_head(self.ln_f(x))


def gpt_pipe_layers(cfg):
    """Flat layer list for PipelineLayer (GPTForCausalLMPipe role; pipeline
    requires untied embeddings — the reference shares them via
    SharedLayerDesc + grad allreduce, planned for the interleaved milestone)."""
    assert not cfg.tie_embeddings, "pipeline GPT needs tie_embeddings=False"
    return ([GPTEmbeddingStage(cfg)] +
            [GPTBlock(cfg) for _ in range(cfg.num_layers)] +
            [GPTHeadStage(cfg)])


def gpt_tiny(**kw):
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 128)
    return GPTConfig(**kw)


def gpt_1p3b(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=16, max_seq_len=2048, **kw)


def gpt_6p7b(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=4096, num_layers=32,
                     num_heads=32, max_seq_len=2048, **kw)


def llama_7b(**kw):
    kw.setdefault("use_rope", True)
    kw.setdefault("use_rmsnorm", True)
    kw.setdefault("use_swiglu", True)
    kw.setdefault("tie_embeddings", False)
    return GPTConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                     num_heads=32, max_seq_len=2048, **kw)
