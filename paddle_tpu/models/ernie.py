"""ERNIE-style bidirectional encoder (BASELINE config 4's named model
family; role parity: the ERNIE-3.0 encoders the reference ecosystem
trains through `paddle.nn.TransformerEncoder` —
python/paddle/nn/layer/transformer.py:646 — with MLM+NSP pretraining
heads).

TPU-first notes: the encoder rides this framework's `nn.Transformer*`
stack, so full-sequence bidirectional attention runs the fused-softmax
path on CPU and the additive-bias flash kernels on TPU (the padding mask
is a stop-gradient additive bias, streamed blockwise — docs/ATTENTION.md
"additive/boolean masks" row). The MLM decoder ties the word-embedding
matrix (transposed matmul, MXU-shaped); masked positions score through
the whole [B,S,V] only at encoder scale (S<=512 typical), so the cut-CE
machinery is not needed here.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..nn import functional as F

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForPretraining",
           "ErniePretrainingCriterion", "ernie_tiny", "ernie_base",
           "ernie_3_0_medium"]


class ErnieConfig:
    def __init__(self, vocab_size=40000, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden=None, max_position=2048,
                 type_vocab_size=4, dropout=0.1, layer_norm_eps=1e-12,
                 pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden = ffn_hidden or 4 * hidden_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.pad_token_id = pad_token_id


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as P

        b, s = input_ids.shape
        if position_ids is None:
            position_ids = P.ops.broadcast_to(
                P.ops.arange(0, s, dtype="int32").unsqueeze(0), [b, s])
        if token_type_ids is None:
            token_type_ids = P.zeros([b, s], "int32")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class ErnieModel(nn.Layer):
    """Encoder trunk. `attention_mask`: [B, S] with 1 for real tokens,
    0 for padding (reference semantics); internally an additive
    stop-gradient bias [B, 1, 1, S] so the fused biased-attention tier
    applies. Returns (sequence_output [B,S,H], pooled_output [B,H])."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.ffn_hidden,
            dropout=cfg.dropout, activation="gelu",
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        import paddle_tpu as P

        if attention_mask is None:
            attention_mask = (
                input_ids != self.cfg.pad_token_id).astype("float32")
        if attention_mask.ndim == 2:
            # additive bias: 0 where attendable, -1e4 on padding. Only
            # the mask WE build is stamped stop_gradient (routing it to
            # the zero-cotangent biased flash kernel); a caller-supplied
            # 4-D bias keeps its own flag — flipping it here would
            # silently kill a trainable bias's gradient
            attention_mask = ((1.0 - attention_mask.astype("float32"))
                              * -1e4).unsqueeze(1).unsqueeze(1)
            attention_mask.stop_gradient = True
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = self.encoder(x, attention_mask)
        pooled = P.ops.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """MLM (decoder tied to the word embeddings) + NSP/sentence-order
    head — the ERNIE pretraining objective pair."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True,
            default_initializer=lambda *_: np.zeros(cfg.vocab_size,
                                                    np.float32))
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        import paddle_tpu as P

        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        w = self.ernie.embeddings.word_embeddings.weight  # [V, H]
        logits = P.ops.matmul(h, w, transpose_y=True) + self.mlm_bias
        return logits, self.nsp_head(pooled)


class ErniePretrainingCriterion(nn.Layer):
    """MLM CE over masked positions (labels == ignore_index elsewhere)
    plus NSP CE; both terms are masked means, summed."""

    def __init__(self, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self.ce = nn.CrossEntropyLoss(ignore_index=ignore_index)
        self.nsp_ce = nn.CrossEntropyLoss()

    def forward(self, prediction_logits, nsp_logits, masked_lm_labels,
                next_sentence_labels=None):
        v = prediction_logits.shape[-1]
        mlm = self.ce(prediction_logits.reshape([-1, v]),
                      masked_lm_labels.reshape([-1]))
        if next_sentence_labels is None:
            return mlm
        return mlm + self.nsp_ce(nsp_logits,
                                 next_sentence_labels.reshape([-1]))


def ernie_tiny(**kw):
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_position", 128)
    return ErnieConfig(**kw)


def ernie_base(**kw):
    kw.setdefault("vocab_size", 40000)
    kw.setdefault("hidden_size", 768)
    kw.setdefault("num_layers", 12)
    kw.setdefault("num_heads", 12)
    return ErnieConfig(**kw)


def ernie_3_0_medium(**kw):
    kw.setdefault("num_layers", 6)
    return ernie_base(**kw)
