"""Auto-regressive generation over static KV caches.

Role parity: the reference's decode serving path — `AnalysisPredictor` +
`masked_multihead_attention`/`block_multi_head_attention` decode kernels
(`paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu`) and
the generation loops its ecosystem builds on them.

TPU-first design: the naive concat KV cache grows the sequence axis every
token — a new shape per step, so XLA recompiles per token. Here the cache
is a FIXED-shape buffer `[B, H, max_len, D]` per layer written with
`lax.dynamic_update_slice` at a traced position, so generation compiles
exactly twice (one prefill program, one decode-step program) regardless
of length. The decode step attends with the Pallas `decode_attention`
kernel on TPU (position-masked paged read, logits never materialized) and
tokens stay on device between steps — the host loop dispatches
asynchronously and fetches once at the end (or per step only when
`eos_token_id` needs checking).
"""
from __future__ import annotations

import contextlib
import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flags, rng
from ..core.tensor import Tensor

# decode steps per compiled lax.scan dispatch (generate's fast path): the
# host leaves the token loop for this many steps at a time
DECODE_CHUNK = 32

# --- warm (cached-prefix) tail prefill -------------------------------------
# Trace-time switch for prefix caching (inference/engine, ISSUE 13): a
# multi-token dense forward normally assumes cache_pos == 0 and attends
# only its own fresh K/V (cold prefill).  Inside `warm_prefill_guard(P)`
# the same forward is a WARM TAIL PREFILL: the dense cache buffers
# arrive pre-loaded with a cached prefix at [0, P) (P is a TRACED
# page-aligned scalar), the fresh tokens write at [P, P+S), and every
# query attends the prefix plus the causal fresh span.  A thread-local
# rather than a model kwarg: the flag is static PER TRACE (the engine
# enters the guard inside its jitted cached-prefill program), so no
# model-family forward signature has to grow a parameter.
_WARM_PREFILL = threading.local()


@contextlib.contextmanager
def warm_prefill_guard(prefix_len):
    """`prefix_len`: traced int32 scalar — the number of cached prefix
    tokens already sitting in the dense cache buffers at [0, P)."""
    prev = getattr(_WARM_PREFILL, "value", None)
    _WARM_PREFILL.value = prefix_len
    try:
        yield
    finally:
        _WARM_PREFILL.value = prev


def _static_cache_attention(q, k, v, kv_cache, cache_pos, attn_start=None):
    """Shared attention-over-static-cache body for the model families.

    q: [B, S, Hq, D]; k/v: [B, S, Hkv, D] (GQA: Hkv may divide Hq — the
    cache stores KV heads, NOT expanded query heads, so GQA's decode
    bandwidth advantage survives); kv_cache: (k_buf, v_buf) Tensors
    [B, Hkv, max_len, D]; cache_pos: scalar int Tensor — write offset of
    this call's tokens; attn_start: optional [B] int Tensor — first
    NON-PAD position per row (left-padded ragged prompts). Prefill
    (S > 1) assumes cache_pos == 0 and runs causal attention over the
    fresh K/V (with pad columns masked); decode (S == 1) reads the cache
    through the Pallas `decode_attention` kernel (grouped queries per KV
    head), masked to attn_start <= j <= cache_pos.
    Returns (out [B, S, Hq, D], (k_buf, v_buf)).

    Paged tier (inference/engine): a 3-tuple kv_cache
    ``(k_pages, v_pages, page_table)`` with a per-row [B] cache_pos
    vector routes to `_paged_cache_attention` — per-sequence ragged
    positions over a shared page pool instead of the lockstep dense
    buffers."""
    import importlib

    from .. import ops
    from ..core.dispatch import apply
    from ..nn import functional as F

    if isinstance(kv_cache, (tuple, list)) and len(kv_cache) in (3, 5):
        return _paged_cache_attention(q, k, v, kv_cache, cache_pos)

    DA = importlib.import_module("paddle_tpu.ops.pallas.decode_attention")

    b, s, hq, d = q.shape
    hkv = k.shape[2]
    if s == 1:
        # decode step: [B,1,Hkv,D] -> [B,Hkv,1,D] is a pure reshape
        # (identical element order) — the cache write stays
        # transpose-free on the per-token hot path (PT401 budget on
        # the scanned decode program holds this at zero new relayouts)
        kt = ops.reshape(k, [b, hkv, 1, d])
        vt = ops.reshape(v, [b, hkv, 1, d])
    else:
        kt = ops.transpose(k, [0, 2, 1, 3])
        vt = ops.transpose(v, [0, 2, 1, 3])
    kb, vb = kv_cache

    def upd(buf, new, p):
        return jax.lax.dynamic_update_slice(
            buf, new.astype(buf.dtype), (0, 0, p, 0))

    kb = apply("kv_cache_update", upd, kb, kt, cache_pos)
    vb = apply("kv_cache_update", upd, vb, vt, cache_pos)
    if s == 1:
        def dec(q1, kb_, vb_, p, st):
            pos = jnp.broadcast_to(p, (q1.shape[0],))
            return DA.decode_attention(q1, kb_, vb_, pos, start=st)

        q1 = q.reshape([b, hq, d])
        out = apply("decode_attention", dec, q1, kb, vb, cache_pos,
                    attn_start)
        out = out.reshape([b, 1, hq, d])
    else:
        wp = getattr(_WARM_PREFILL, "value", None)
        if wp is not None:
            # WARM tail prefill (prefix caching): keys/values come from
            # the CACHE BUFFER — cached prefix at [0, P) plus the fresh
            # tail this call just wrote at [P, P+S) — not from the
            # fresh K/V alone.  Query row i (real iff i >= attn_start)
            # holds absolute position P + i - start; it attends every
            # prefix key (j < P, all real: committed pages carry no
            # padding) and the causal fresh span (start <= j-P <= i).
            # Keys in [P_real, buffer_cap) beyond the written span stay
            # masked, so a bucketed prefix capacity never leaks
            # garbage into the softmax.
            cap = kb.shape[2]
            kk = ops.transpose(kb, [0, 2, 1, 3])      # [B, cap, Hkv, D]
            vv = ops.transpose(vb, [0, 2, 1, 3])
            if hkv != hq:
                rep = hq // hkv
                kk = ops.repeat_interleave(kk, rep, axis=2)
                vv = ops.repeat_interleave(vv, rep, axis=2)
            st = attn_start if attn_start is not None \
                else ops.zeros([b], dtype="int32")

            def build_warm_mask(st_, p_):
                j = jnp.arange(cap)[None, None, :]    # key column
                i = jnp.arange(s)[None, :, None]      # query row
                jj = j - p_                           # fresh-span index
                valid = (j < p_) | ((jj >= st_[:, None, None])
                                    & (jj <= i))
                return jnp.where(valid[:, None], 0.0, -1e30)

            mask = apply("warm_prefill_mask", build_warm_mask, st,
                         wp if isinstance(wp, Tensor) else Tensor(wp))
            out = F.scaled_dot_product_attention(
                q, kk, vv, attn_mask=mask, dropout_p=0.0,
                training=False)
            return out, (kb, vb)
        if hkv != hq:
            rep = hq // hkv
            k = ops.repeat_interleave(k, rep, axis=2)
            v = ops.repeat_interleave(v, rep, axis=2)
        mask = None
        if attn_start is not None:
            def build_mask(st):
                j = jnp.arange(s)[None, :]                    # key pos
                i = jnp.arange(s)[:, None]                    # query pos
                valid = (j <= i)[None] & (j[None] >= st[:, None, None])
                return jnp.where(valid[:, None], 0.0, -1e30)  # [B,1,S,S]

            mask = apply("prefill_pad_mask", build_mask, attn_start)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, dropout_p=0.0, training=False)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=0.0, training=False)
    return out, (kb, vb)


def _paged_cache_attention(q, k, v, kv_cache, cache_pos):
    """Paged decode attention (inference/engine tier).

    q: [B, 1, Hq, D]; k/v: [B, 1, Hkv, D]; kv_cache:
    ``(k_pages, v_pages, page_table)`` Tensors — pools
    [num_pages, Hkv, page_size, D] shared across sequences, page_table
    [B, P] int32 (unused tail entries point at the reserved scratch
    page 0); cache_pos: [B] int32 Tensor — each row's write index (==
    its current length).  The current token's K/V scatters into the
    row's live page at (page_table[b, pos//ps], pos % ps), then the
    ragged paged-attention kernel attends 0..pos[b] per row.  Free/dead
    batch slots ride along with pos=0 and an all-scratch page table —
    their writes land in page 0 and their outputs are discarded by the
    engine, so the compiled shape never changes with occupancy.

    Quantized KV tier (ISSUE 12): a 5-tuple
    ``(k_pages, v_pages, page_table, k_scales, v_scales)`` with int8
    pools and per-token-per-head scale tables
    [num_pages, Hkv, page_size].  The write path quantizes each fresh
    K/V head-vector independently (`ops.quant.quantize_vectors` — no
    neighbour requantization, so page writes stay single-slot
    scatters), stores int8 + scale, and the attention dequantizes in
    VMEM.  Returns (out [B, 1, Hq, D], new kv_cache of the same
    arity)."""
    import importlib

    from ..core.dispatch import apply

    PA = importlib.import_module("paddle_tpu.ops.pallas.paged_attention")

    b, s, hq, d = q.shape
    hkv = k.shape[2]
    if s != 1:
        raise ValueError(
            "paged KV cache serves single-token decode steps; prefill "
            "runs the dense path and packs into pages afterwards")
    quantized = len(kv_cache) == 5
    if quantized:
        kp, vp, pt, ks, vs = kv_cache
    else:
        kp, vp, pt = kv_cache
        ks = vs = None
    ps = kp.shape[2]

    def write(pool, new, pt_, pos_):
        page_ids = pt_[jnp.arange(b), pos_ // ps]       # [B]
        slots = pos_ % ps
        return pool.at[page_ids, :, slots, :].set(new.astype(pool.dtype))

    def write_q(pool, scales, new, pt_, pos_):
        from ..ops.quant import quantize_vectors

        page_ids = pt_[jnp.arange(b), pos_ // ps]       # [B]
        slots = pos_ % ps
        qv, sv = quantize_vectors(new)                  # [B,Hkv,D]/[B,Hkv]
        pool = pool.at[page_ids, :, slots, :].set(qv)
        scales = scales.at[page_ids, :, slots].set(sv)
        return pool, scales

    k1 = k.reshape([b, hkv, d])
    v1 = v.reshape([b, hkv, d])
    if quantized:
        kp, ks = apply("paged_kv_update", write_q, kp, ks, k1, pt,
                       cache_pos)
        vp, vs = apply("paged_kv_update", write_q, vp, vs, v1, pt,
                       cache_pos)
    else:
        kp = apply("paged_kv_update", write, kp, k1, pt, cache_pos)
        vp = apply("paged_kv_update", write, vp, v1, pt, cache_pos)

    def attend(q1, kp_, vp_, pt_, pos_, ks_, vs_):
        return PA.paged_attention_dispatch(q1, kp_, vp_, pt_, pos_,
                                           k_scales=ks_, v_scales=vs_)

    out = apply("paged_attention", attend, q.reshape([b, hq, d]), kp, vp,
                pt, cache_pos, ks, vs)
    new_cache = (kp, vp, pt, ks, vs) if quantized else (kp, vp, pt)
    return out.reshape([b, 1, hq, d]), new_cache


def decode_position_ids(cache_pos, b, s, attn_start=None):
    """[B, S] position ids for a cached forward.  cache_pos is a scalar
    Tensor (dense lockstep cache: every row at the same offset) or a
    per-row [B] vector (paged ragged cache: each sequence at its own
    length).  Applies the left-pad `shift_positions` when attn_start is
    given.  Shared by the model families' rope/learned-position
    branches."""
    from .. import ops

    pos = ops.arange(0, s, dtype="int32")
    if len(cache_pos.shape) == 1:
        position_ids = cache_pos.unsqueeze(1) + pos.unsqueeze(0)
    else:
        row = pos + cache_pos
        position_ids = ops.broadcast_to(row.unsqueeze(0), [b, s])
    return shift_positions(position_ids, attn_start)


def shift_positions(position_ids, attn_start):
    """Per-row position shift for left-padded prompts: each row's first
    real token sits at position 0 (pad rows clip to 0). Shared by the
    model families' rope/learned-position branches."""
    from .. import ops

    if attn_start is None:
        return position_ids
    return ops.clip(position_ids - attn_start.unsqueeze(1), min=0)


def init_kv_caches(num_layers, batch, num_heads, head_dim, max_len,
                   dtype="float32"):
    """Fixed-shape per-layer KV buffers; capacity rounds up to a multiple
    of 128 so the decode kernel's block sizes always divide it (the tail
    is masked by position)."""
    cap = -(-int(max_len) // 128) * 128
    return [(jnp.zeros((batch, num_heads, cap, head_dim), dtype),
             jnp.zeros((batch, num_heads, cap, head_dim), dtype))
            for _ in range(num_layers)]


def _sample(logits, key, do_sample, temperature, top_k):
    """logits: [B, V] f32. Returns [B] int32 next tokens."""
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature != 1.0:
        logits = logits / max(float(temperature), 1e-6)
    if top_k:
        # clamp: top_k >= vocab would index past the sorted axis (jnp wraps
        # negative OOB to 0, silently disabling the filter) — k == vocab
        # keeps every logit, which is the correct no-op
        k = min(int(top_k), logits.shape[-1])
        kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class GenerationMixin:
    """Mixed into *ForCausalLM models that implement
    `init_kv_caches(batch, max_len)` and
    `forward(ids, kv_caches=, cache_pos=) -> (logits, new_caches)`."""

    def _model_run(self, params, buffers, step_ids, caches, pos,
                   start):
        """One cached-forward model invocation on raw jax values (shared
        by the greedy/sampling and beam program builders — the model-call
        contract lives in exactly one place)."""
        with flags.no_grad_guard(), flags.trace_guard():
            with self.bind_state(params, buffers):
                logits, new_caches = self(
                    Tensor(step_ids),
                    kv_caches=[(Tensor(k), Tensor(v)) for k, v in caches],
                    cache_pos=Tensor(pos),
                    attn_start=(None if start is None else Tensor(start)))
        return (logits._value,
                [(k._value, v._value) for k, v in new_caches])

    def _gen_programs(self, b, s0, cap, do_sample, temperature, top_k,
                      has_mask):
        """Compiled prefill program, cached per signature — a serving
        loop calling generate() repeatedly must not pay the XLA compile
        per call. (Decode runs through `_decode_chunk_program`.)"""
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        sig = (b, s0, cap, bool(do_sample), float(temperature), int(top_k),
               bool(has_mask))
        hit = cache.get(sig)
        if hit is not None:
            return hit

        run = self._model_run

        @jax.jit
        def prefill(params, buffers, ids, caches, start):
            logits, caches = run(params, buffers, ids, caches,
                                 jnp.zeros((), jnp.int32), start)
            return logits[:, -1, :], caches

        cache[sig] = prefill
        return cache[sig]

    def _decode_chunk_program(self, n, b, cap, do_sample, temperature,
                              top_k, has_mask, eos_token_id):
        """n decode steps inside ONE compiled lax.scan (TPU-first: the
        per-token python loop pays a host dispatch per token — tens of ms
        through a tunneled PJRT — while the kernel itself is ~1 ms; the
        scan removes the host from the loop entirely). Bit-identical to
        n iterations of the single-step path: the PRNG split order, eos
        freezing, and cache updates follow the same sequence. Caches are
        donated: each step overwrites one position per buffer, and
        donation lets XLA update in place instead of copying
        ~2*L*B*H*max*D bytes every token."""
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        sig = ("chunk", n, b, cap, bool(do_sample), float(temperature),
               int(top_k), bool(has_mask),
               -1 if eos_token_id is None else int(eos_token_id))
        hit = cache.get(sig)
        if hit is not None:
            return hit
        run = self._model_run

        @functools.partial(jax.jit, donate_argnums=(3,))
        def decode_n(params, buffers, tok, caches, pos0, key, start,
                     finished):
            def body(carry, i):
                tok, caches, key, finished = carry
                key, sub = jax.random.split(key)
                logits, caches = run(params, buffers, tok[:, None],
                                     caches, pos0 + i, start)
                nxt = _sample(logits[:, -1, :], sub, do_sample,
                              temperature, top_k)
                if eos_token_id is not None:
                    # frozen rows keep emitting eos, not live continuations
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                return (nxt, caches, key, finished), (nxt, finished.all())

            (tok, caches, key, finished), (toks, fin_all) = jax.lax.scan(
                body, (tok, caches, key, finished),
                jnp.arange(n, dtype=jnp.int32))
            return toks.T, tok, caches, key, finished, fin_all

        cache[sig] = decode_n
        return decode_n

    # ---- beam search ----
    def _beam_programs(self, b, n, s0, cap, eos_id, length_penalty):
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        sig = ("beam", b, n, s0, cap, eos_id, float(length_penalty))
        hit = cache.get(sig)
        if hit is not None:
            return hit

        run = self._model_run

        @jax.jit
        def beam_prefill(params, buffers, ids, caches):
            logits, caches = run(params, buffers, ids, caches,
                                 jnp.zeros((), jnp.int32), None)
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), axis=-1)
            scores, toks = jax.lax.top_k(logp, n)        # [B, N]
            # tile each row's cache N times: beam i of row b at b*N+i
            caches = [(jnp.repeat(k, n, axis=0), jnp.repeat(v, n, axis=0))
                      for k, v in caches]
            return toks.astype(jnp.int32), scores, caches

        def pool_update(step_idx, tok, scores, lengths, pool):
            """Move hypotheses that just emitted eos into the per-row
            finished pool (best-so-far by length-normalized score), and
            knock their beam slots out of the live search."""
            fin_norm, fin_step, fin_beam = pool
            done = tok == eos_id                              # [B, N]
            norm = scores / (jnp.maximum(lengths, 1.0) ** length_penalty)
            cand = jnp.where(done, norm, -jnp.inf)
            best_c = jnp.argmax(cand, axis=1)                 # [B]
            best_v = jnp.take_along_axis(cand, best_c[:, None], 1)[:, 0]
            better = best_v > fin_norm
            fin_norm = jnp.where(better, best_v, fin_norm)
            fin_step = jnp.where(better, step_idx, fin_step)
            fin_beam = jnp.where(better, best_c.astype(jnp.int32),
                                 fin_beam)
            scores = jnp.where(done, -1e30, scores)   # slot leaves the beam
            return scores, (fin_norm, fin_step, fin_beam)

        def beam_step(params, buffers, tok, caches, pos, scores, lengths,
                      pool, step_idx):
            # plain traceable body — jitted by the scanned program below
            # (the whole beam loop runs in ONE dispatch; see
            # _beam_scan_program)
            # tok: [B, N]; scores: [B, N] running log-probs (finished
            # slots already at -1e30); lengths: [B, N] tokens generated
            logits, caches = run(params, buffers,
                                 tok.reshape(b * n)[:, None], caches, pos,
                                 None)
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), axis=-1)
            v = logp.shape[-1]
            total = scores[:, :, None] + logp.reshape(b, n, v)
            new_scores, flat = jax.lax.top_k(total.reshape(b, n * v), n)
            parent = (flat // v).astype(jnp.int32)            # [B, N]
            new_tok = (flat % v).astype(jnp.int32)
            # reorder caches to the chosen parents
            gather = (jnp.arange(b)[:, None] * n + parent).reshape(-1)
            caches = [(k[gather], v_[gather]) for k, v_ in caches]
            new_lengths = jnp.take_along_axis(lengths, parent, axis=1) + 1.0
            if eos_id is not None:
                new_scores, pool = pool_update(
                    step_idx, new_tok, new_scores, new_lengths, pool)
            return new_tok, new_scores, parent, new_lengths, pool, caches

        cache[sig] = (beam_prefill, beam_step, pool_update)
        return cache[sig]

    def _beam_scan_program(self, steps, b, n, s0, cap, eos_id,
                           length_penalty):
        """steps-1 beam steps inside ONE compiled lax.scan (the beam loop
        has no early exit, so the entire search after prefill is a single
        dispatch; the per-step (tok, parent) history for backtracking is
        the scan's stacked output). Caches donated, as in greedy decode."""
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        sig = ("beamscan", steps, b, n, cap,
               -1 if eos_id is None else int(eos_id),
               float(length_penalty))
        hit = cache.get(sig)
        if hit is not None:
            return hit
        _, beam_step, _ = self._beam_programs(b, n, s0, cap, eos_id,
                                              length_penalty)

        @functools.partial(jax.jit, donate_argnums=(3,))
        def beam_scan(params, buffers, tok, caches, pos0, scores, lengths,
                      pool):
            def body(carry, i):
                tok, scores, lengths, pool, caches = carry
                tok, scores, parent, lengths, pool, caches = beam_step(
                    params, buffers, tok, caches, pos0 + i - 1, scores,
                    lengths, pool, i)
                return (tok, scores, lengths, pool, caches), (tok, parent)

            carry, hist = jax.lax.scan(
                body, (tok, scores, lengths, pool, caches),
                jnp.arange(1, steps, dtype=jnp.int32))
            tok, scores, lengths, pool, caches = carry
            return tok, scores, lengths, pool, caches, hist

        cache[sig] = beam_scan
        return beam_scan

    def _beam_search(self, ids, max_new_tokens, num_beams, eos_token_id,
                     length_penalty):
        b, s0 = ids.shape
        n = num_beams
        params, buffers = self.functional_state()
        caches = self.init_kv_caches(b, s0 + max_new_tokens)
        # prefill at batch B (tiling N identical prefills would waste N-1x)
        cap = caches[0][0].shape[2]
        beam_prefill, _, pool_update = self._beam_programs(
            b, n, s0, cap, eos_token_id, length_penalty)

        tok, scores, caches = beam_prefill(params, buffers, ids, caches)
        lengths = jnp.ones((b, n), jnp.float32)  # 1 generated token so far
        # finished-hypothesis pool: best length-normalized score per row
        # plus the (step, beam) to backtrack from — a completed sequence
        # is never evicted by live continuations (review r3 finding)
        pool = (jnp.full((b,), -jnp.inf),
                jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32))
        if eos_token_id is not None:
            scores, pool = pool_update(0, tok, scores, lengths, pool)
        tok0 = tok
        par0 = jnp.tile(jnp.arange(n), (b, 1))
        if max_new_tokens > 1:
            beam_scan = self._beam_scan_program(
                max_new_tokens, b, n, s0, cap, eos_token_id,
                length_penalty)
            tok, scores, lengths, pool, caches, (toks_s, pars_s) = \
                beam_scan(params, buffers, tok, caches,
                          jnp.asarray(s0, jnp.int32), scores, lengths,
                          pool)
            toks_all = np.concatenate(
                [np.asarray(jax.device_get(tok0))[None],
                 np.asarray(jax.device_get(toks_s))])
            parents_all = np.concatenate(
                [np.asarray(jax.device_get(par0))[None],
                 np.asarray(jax.device_get(pars_s))])
        else:
            toks_all = np.asarray(jax.device_get(tok0))[None]
            parents_all = np.asarray(jax.device_get(par0))[None]
        # pick per row: best finished hypothesis vs best live beam
        steps = max_new_tokens
        live_norm = scores / (jnp.maximum(lengths, 1.0) ** length_penalty)
        live_best = jnp.argmax(live_norm, axis=1)
        live_val = jnp.take_along_axis(live_norm, live_best[:, None],
                                       1)[:, 0]
        fin_norm, fin_step, fin_beam = pool
        use_fin = fin_norm >= live_val
        sel_step = np.asarray(jax.device_get(
            jnp.where(use_fin, fin_step, steps - 1)))
        sel_beam = np.asarray(jax.device_get(
            jnp.where(use_fin, fin_beam, live_best.astype(jnp.int32))))
        # rows whose winner finished at sel_step keep an eos-filled tail
        # (rectangular output)
        eos_fill = eos_token_id if eos_token_id is not None else 0
        out = np.full((b, steps), eos_fill, np.int32)
        beam = sel_beam.copy()
        rows = np.arange(b)
        for t in range(steps - 1, -1, -1):
            take = t <= sel_step
            out[take, t] = toks_all[t][rows[take], beam[take]]
            beam[take] = parents_all[t][rows[take], beam[take]]
        return Tensor(jnp.concatenate([ids, jnp.asarray(out)], axis=1))

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, eos_token_id=None, seed=None,
                 attention_mask=None, num_beams=1, length_penalty=1.0):
        """input_ids: [B, S0] int Tensor/array. Returns an int32 Tensor
        [B, S0 + n_generated]. With eos_token_id set, rows that emit eos
        are frozen (their remaining positions fill with eos) and the loop
        stops once every row has finished. attention_mask: optional
        [B, S0] 0/1 mask for LEFT-padded ragged prompts — pad positions
        never contribute to attention and rotary/learned positions start
        at each row's first real token. num_beams > 1 switches to beam
        search (greedy scoring only; finished hypotheses live in a pool
        and the best length_penalty-normalized sequence wins; incompatible
        with do_sample and attention_mask)."""
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, s0 = ids.shape
        if max_new_tokens <= 0:
            return Tensor(ids)
        if num_beams > 1:
            if do_sample:
                raise ValueError("beam search with do_sample is not "
                                 "supported; use num_beams=1 for sampling")
            if attention_mask is not None:
                raise ValueError("beam search over left-padded ragged "
                                 "batches is not supported yet")
            was_training = self.training
            self.eval()
            try:
                return self._beam_search(ids, max_new_tokens, num_beams,
                                         eos_token_id, length_penalty)
            finally:
                if was_training:
                    self.train()
        start = None
        if attention_mask is not None:
            m = attention_mask._value if isinstance(attention_mask, Tensor) \
                else jnp.asarray(attention_mask)
            m = m.astype(jnp.int32)
            if m.shape != (b, s0):
                raise ValueError(
                    f"attention_mask must be [B, S0]={b, s0}, "
                    f"got {tuple(m.shape)}")
            mh = np.asarray(jax.device_get(m))
            if not (mh[:, -1] == 1).all():
                raise ValueError(
                    "attention_mask must be LEFT-padded (last column all "
                    "ones): right padding would put a pad token at the "
                    "next-token prediction position")
            starts_h = mh.argmax(axis=1)
            rows = np.arange(b)[:, None]
            if not ((np.arange(s0)[None, :] >= starts_h[:, None])
                    == mh[rows, np.arange(s0)[None, :]].astype(bool)).all():
                raise ValueError(
                    "attention_mask must be contiguous left padding "
                    "(zeros then ones per row)")
            # left-padding: first real token = number of leading zeros
            start = jnp.asarray(starts_h, jnp.int32)
        max_len = s0 + max_new_tokens
        was_training = self.training
        self.eval()
        try:
            params, buffers = self.functional_state()
            caches = self.init_kv_caches(b, max_len)
            cap = caches[0][0].shape[2]
            prefill = self._gen_programs(
                b, s0, cap, do_sample, temperature, top_k,
                start is not None)
            key = (jax.random.PRNGKey(seed) if seed is not None
                   else rng.default_generator.split())

            last_logits, caches = prefill(params, buffers, ids, caches,
                                          start)
            key, sub = jax.random.split(key)
            tok = _sample(last_logits, sub, do_sample, temperature, top_k)
            finished = jnp.zeros((b,), bool)
            if eos_token_id is not None:
                finished = tok == eos_token_id
            # chunked scanned decode: CHUNK tokens per host dispatch (the
            # per-token loop paid one dispatch — tens of ms on tunneled
            # PJRT — per ~1 ms kernel). Token stream, PRNG order, and eos
            # freezing are bit-identical to the single-step path; the
            # all-finished early-exit is checked once per chunk and the
            # exact per-token stop length restored by the trim below.
            # Without an eos there is nothing to check between chunks —
            # the decode runs as ONE scanned dispatch for lengths up to
            # 128 (same recurrence, larger n, identical token/PRNG
            # stream). The 128 cap bounds per-length program compiles: a
            # caller sweeping long lengths reuses the n=128 program for
            # full chunks (tail-chunk programs were always per-length).
            CHUNK = (DECODE_CHUNK if eos_token_id is not None
                     else max(1, min(max_new_tokens - 1, 128)))
            chunks = [tok[:, None]]
            fin_alls = [finished.all()[None]]
            i = 1
            while i < max_new_tokens:
                if eos_token_id is not None and bool(
                        np.asarray(jax.device_get(finished.all()))):
                    break
                n = min(CHUNK, max_new_tokens - i)
                decode_n = self._decode_chunk_program(
                    n, b, cap, do_sample, temperature, top_k,
                    start is not None, eos_token_id)
                toks, tok, caches, key, finished, fin_all = decode_n(
                    params, buffers, tok, caches,
                    jnp.asarray(s0 + i - 1, jnp.int32), key, start,
                    finished)
                chunks.append(toks)
                fin_alls.append(fin_all)
                i += n
            gen = jnp.concatenate(chunks, axis=1)
            if eos_token_id is not None and gen.shape[1] > 1:
                # trim to the single-step loop's stop point: it breaks
                # BEFORE step j+1 when all rows were finished after step
                # j, so keep j+1 tokens for the earliest such j
                fin_h = np.asarray(
                    jax.device_get(jnp.concatenate(fin_alls)))
                hits = np.flatnonzero(fin_h)
                if hits.size:
                    gen = gen[:, :int(hits[0]) + 1]
            return Tensor(jnp.concatenate([ids, gen], axis=1))
        finally:
            if was_training:
                self.train()
