"""LLaMA family — decoder-only with GQA (BASELINE config 4: LLaMA-7B PP).

Role parity: the reference trains LLaMA through the same Fleet mpu stack as
GPT (PaddleNLP-style usage of `fleet/layers/mpu/`, SURVEY §2.5); the fused
ops it leans on — `fused_rms_norm`, `fused_rotary_position_embedding`,
`swiglu` (`python/paddle/incubate/nn/functional/`) — map to this module's
RMSNorm/RoPE/SwiGLU blocks backed by the Pallas/XLA fused paths.

Beyond the GPT module, this adds grouped-query attention (num_kv_heads <
num_heads): KV projections shrink to the KV-head count and are repeated at
attention time — under TP the KV heads shard over the mp axis like Q heads.
Pipeline stages are exported for the 1F1B/interleaved schedules.
"""
from __future__ import annotations

from .. import nn
from ..distributed import mpu
from ..distributed.recompute import recompute as _recompute
from ..nn import functional as F
from .generation import GenerationMixin, _static_cache_attention

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "llama_pipe_layers",
           "llama_tiny", "llama_7b", "llama_13b", "llama2_70b_shapes"]


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096, num_layers=32,
                 num_heads=32, num_kv_heads=None, max_seq_len=2048,
                 ffn_hidden=11008, rope_theta=10000.0, rms_eps=1e-6,
                 dropout=0.0, tie_embeddings=False, recompute=False,
                 recompute_policy=None, sequence_parallel=False,
                 context_parallel=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.max_seq_len = max_seq_len
        self.ffn_hidden = ffn_hidden
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        self.dropout = dropout
        self.tie_embeddings = tie_embeddings
        self.recompute = recompute
        # named remat policy: None/'full' | 'dots' | 'dots_no_batch'
        self.recompute_policy = recompute_policy
        self.sequence_parallel = sequence_parallel
        self.context_parallel = context_parallel


class LlamaAttention(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        q_size = cfg.num_heads * self.head_dim
        kv_size = cfg.num_kv_heads * self.head_dim
        # fused qkv column-parallel: [q | k | v] heads shard together
        self.qkv_proj = mpu.ColumnParallelLinear(
            cfg.hidden_size, q_size + 2 * kv_size, gather_output=False,
            has_bias=False)
        self.out_proj = mpu.RowParallelLinear(
            q_size, cfg.hidden_size, input_is_parallel=True, has_bias=False)

    def forward(self, x, cache=None, kv_cache=None, cache_pos=None,
                attn_start=None):
        from .. import ops

        b, s, _ = x.shape
        hd = self.head_dim
        qkv = self.qkv_proj(x)
        q_size = self.num_heads * hd
        kv_size = self.num_kv_heads * hd
        q, k, v = ops.split(qkv, [q_size, kv_size, kv_size], axis=-1)
        q = q.reshape([b, s, self.num_heads, hd])
        k = k.reshape([b, s, self.num_kv_heads, hd])
        v = v.reshape([b, s, self.num_kv_heads, hd])
        position_ids = None
        if kv_cache is not None:
            # static-cache decode: phases continue from the traced offset;
            # left-padded rows start rotary position 0 at their first
            # real token
            from .generation import decode_position_ids

            position_ids = decode_position_ids(cache_pos, b, s,
                                               attn_start)
        elif cache is not None:
            # legacy concat cache: offset is a host int
            import numpy as _np

            offset = cache[0].shape[1]
            position_ids = _np.arange(offset, offset + s)[None, :].repeat(
                b, axis=0)
        q, k, _ = F.fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids,
            rotary_emb_base=self.cfg.rope_theta)
        if cache is not None:
            pk, pv = cache
            k = ops.concat([pk, k], axis=1)
            v = ops.concat([pv, v], axis=1)
            cache = (k, v)
        if kv_cache is not None:
            # GQA-native static cache: k/v stay at num_kv_heads; the decode
            # kernel groups Hq/Hkv queries per KV head so the cache is read
            # once per KV head (GQA's decode-bandwidth advantage)
            out, new_cache = _static_cache_attention(
                q, k, v, kv_cache, cache_pos, attn_start)
            out = self.out_proj(out.reshape([b, s, q_size]))
            return out, new_cache
        if self.num_kv_heads != self.num_heads and \
                self.cfg.context_parallel:
            # ring attention still needs expanded KV; the flash/SDPA path
            # reads GQA heads natively (grouped index maps — KV never
            # expands in HBM, saving Hq/Hkv x of KV traffic)
            rep = self.num_heads // self.num_kv_heads
            k = ops.repeat_interleave(k, rep, axis=2)
            v = ops.repeat_interleave(v, rep, axis=2)
        if self.cfg.context_parallel:
            from ..core.dispatch import apply
            from ..ops.pallas.ring_attention import ring_attention

            out = apply(
                "ring_attention",
                lambda qv, kv, vv: ring_attention(qv, kv, vv, causal=True),
                q, k, v)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.cfg.dropout if self.training else 0.0,
                training=self.training)
        out = self.out_proj(out.reshape([b, s, q_size]))
        if cache is not None:
            return out, cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.gate_up_proj = mpu.ColumnParallelLinear(
            cfg.hidden_size, 2 * cfg.ffn_hidden, gather_output=False,
            has_bias=False)
        self.down_proj = mpu.RowParallelLinear(
            cfg.ffn_hidden, cfg.hidden_size, input_is_parallel=True,
            has_bias=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_up_proj(x)))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.input_norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)
        self.attn = LlamaAttention(cfg)
        self.post_norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def _body(self, x):
        if self.cfg.sequence_parallel:
            x = mpu.sequence_parallel_constraint(x)
        x = x + self.attn(self.input_norm(x))
        return x + self.mlp(self.post_norm(x))

    def forward(self, x, kv_cache=None, cache_pos=None, attn_start=None):
        if kv_cache is not None:
            a, new_cache = self.attn(self.input_norm(x), kv_cache=kv_cache,
                                     cache_pos=cache_pos,
                                     attn_start=attn_start)
            x = x + a
            return x + self.mlp(self.post_norm(x)), new_cache
        if self.cfg.recompute and self.training:
            return _recompute(self._body, x,
                              policy=self.cfg.recompute_policy)
        return self._body(x)


class LlamaModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = mpu.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids, kv_caches=None, cache_pos=None,
                attn_start=None):
        x = self.embed_tokens(input_ids)
        if kv_caches is not None:
            new_caches = []
            for blk, kc in zip(self.layers, kv_caches):
                x, nc = blk(x, kv_cache=kc, cache_pos=cache_pos,
                            attn_start=attn_start)
                new_caches.append(nc)
            return self.norm(x), new_caches
        for blk in self.layers:
            x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = mpu.ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, gather_output=True,
                has_bias=False)

    def forward(self, input_ids, kv_caches=None, cache_pos=None,
                attn_start=None):
        from .. import ops

        if kv_caches is not None:
            h, new_caches = self.model(input_ids, kv_caches=kv_caches,
                                       cache_pos=cache_pos,
                                       attn_start=attn_start)
        else:
            h = self.model(input_ids)
        if self.lm_head is None:
            w = self.model.embed_tokens.weight
            logits = ops.matmul(h, w, transpose_y=True)
        else:
            logits = self.lm_head(h)
        if kv_caches is not None:
            return logits, new_caches
        return logits

    def init_kv_caches(self, batch, max_len):
        from .generation import init_kv_caches

        cfg = self.cfg
        # KV heads only (GQA-native cache; see LlamaAttention.forward)
        return init_kv_caches(cfg.num_layers, batch, cfg.num_kv_heads,
                              cfg.hidden_size // cfg.num_heads, max_len,
                              self.model.embed_tokens.weight.dtype)


class LlamaPretrainingCriterion(nn.Layer):
    def __init__(self, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        loss = F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]),
            ignore_index=self.ignore_index, reduction="mean")
        return loss


class LlamaEmbeddingStage(nn.Layer):
    """Pipeline stage 0 (parity: PipelineLayer LayerDesc split)."""

    def __init__(self, cfg):
        super().__init__()
        self.embed_tokens = mpu.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size)

    def forward(self, input_ids):
        return self.embed_tokens(input_ids)


class LlamaHeadStage(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)
        self.lm_head = mpu.ColumnParallelLinear(
            cfg.hidden_size, cfg.vocab_size, gather_output=True,
            has_bias=False)

    def forward(self, x):
        return self.lm_head(self.norm(x))


def llama_pipe_layers(cfg):
    """Layer list for PipelineModule segmentation (1F1B / interleaved)."""
    return ([LlamaEmbeddingStage(cfg)]
            + [LlamaBlock(cfg) for _ in range(cfg.num_layers)]
            + [LlamaHeadStage(cfg)])


def llama_tiny(**kw):
    d = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
             num_kv_heads=2, max_seq_len=128, ffn_hidden=256)
    d.update(kw)
    return LlamaConfig(**d)


def llama_7b(**kw):
    d = dict(vocab_size=32000, hidden_size=4096, num_layers=32,
             num_heads=32, max_seq_len=2048, ffn_hidden=11008)
    d.update(kw)
    return LlamaConfig(**d)


def llama_13b(**kw):
    d = dict(vocab_size=32000, hidden_size=5120, num_layers=40,
             num_heads=40, max_seq_len=2048, ffn_hidden=13824)
    d.update(kw)
    return LlamaConfig(**d)


def llama2_70b_shapes(**kw):
    d = dict(vocab_size=32000, hidden_size=8192, num_layers=80,
             num_heads=64, num_kv_heads=8, max_seq_len=4096,
             ffn_hidden=28672)
    d.update(kw)
    return LlamaConfig(**d)
