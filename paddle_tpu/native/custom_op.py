"""Runtime custom-op registration: user C++ → shared lib → paddle op.

Role parity: `paddle/fluid/framework/custom_operator.cc` +
`python/paddle/utils/cpp_extension/` — the reference JIT-compiles user
C++/CUDA op sources at runtime and registers them into the op registry.

TPU-first design: the accelerator compute path belongs to XLA/Pallas, so a
user C++ kernel is a HOST op. Sources are compiled with g++ to a shared
library (ctypes ABI — pybind11 is not in this image), and each exported
kernel becomes a paddle op that
  * runs directly in eager mode,
  * runs under `jax.jit` (including on TPU) through `jax.pure_callback`
    — XLA calls back to the host for exactly this op, everything around
    it stays compiled,
  * supports autodiff when a companion gradient symbol is exported
    (wired as a `jax.custom_vjp`).

C ABI contract (elementwise, f32, broadcast-free — inputs same shape):
    forward : void sym(const float** ins, int n_in, float* out, int64_t n)
    backward: void sym(const float** ins, int n_in, const float* gout,
                       float** gins, int64_t n)
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply

_CACHE_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_build", "custom_ops")
_lock = threading.Lock()

_FWD_ARGTYPES = [ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                 ctypes.c_int,
                 ctypes.POINTER(ctypes.c_float),
                 ctypes.c_int64]
_BWD_ARGTYPES = [ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                 ctypes.c_int,
                 ctypes.POINTER(ctypes.c_float),
                 ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                 ctypes.c_int64]


def _compile(name: str, sources, extra_cflags=None, verbose=False) -> str:
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    h = hashlib.sha256()
    blobs = []
    for s in sources:
        if os.path.exists(s):
            with open(s, "rb") as f:
                blobs.append(f.read())
        else:  # inline source string
            blobs.append(s.encode())
    for b in blobs:
        h.update(b)
    h.update(" ".join(extra_cflags or []).encode())
    so = os.path.join(_CACHE_ROOT, f"{name}-{h.hexdigest()[:16]}.so")
    if os.path.exists(so):
        return so
    srcs = []
    for i, s in enumerate(sources):
        if os.path.exists(s):
            srcs.append(s)
        else:
            p = os.path.join(_CACHE_ROOT, f"{name}-{i}.cc")
            with open(p, "w") as f:
                f.write(s)
            srcs.append(p)
    tmp = f"{so}.tmp.{os.getpid()}"
    cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", tmp]
           + (extra_cflags or []) + srcs)
    if verbose:
        print("[cpp_extension]", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"custom op '{name}' failed to compile:\n{e.stderr}") from e
    os.replace(tmp, so)
    return so


def _f32_ptrs(arrays):
    arr = (ctypes.POINTER(ctypes.c_float) * len(arrays))()
    for i, a in enumerate(arrays):
        arr[i] = a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    return arr


class CustomOpLibrary:
    """Handle returned by `load`: exposes each registered op as an
    attribute (mirrors the reference's generated custom-op module)."""

    def __init__(self, name, so_path, functions):
        self._name = name
        self._so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        for py_name, spec in functions.items():
            setattr(self, py_name, self._make_op(py_name, spec))

    def _host_call(self, sym, n_in):
        fn = getattr(self._lib, sym)
        fn.argtypes = _FWD_ARGTYPES
        fn.restype = None

        def call(*ins):
            ins = [np.ascontiguousarray(np.asarray(a, np.float32))
                   for a in ins]
            out = np.empty_like(ins[0])
            fn(_f32_ptrs(ins), len(ins),
               out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
               out.size)
            return out

        return call

    def _host_grad_call(self, sym):
        fn = getattr(self._lib, sym)
        fn.argtypes = _BWD_ARGTYPES
        fn.restype = None

        def call(gout, *ins):
            ins = [np.ascontiguousarray(np.asarray(a, np.float32))
                   for a in ins]
            gout = np.ascontiguousarray(np.asarray(gout, np.float32))
            gins = [np.empty_like(i) for i in ins]
            fn(_f32_ptrs(ins), len(ins),
               gout.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
               _f32_ptrs(gins), gout.size)
            return tuple(gins)

        return call

    def _make_op(self, py_name, spec):
        sym = spec["symbol"]
        grad_sym = spec.get("grad_symbol")
        host_fwd = self._host_call(sym, spec.get("n_inputs", 1))
        host_bwd = self._host_grad_call(grad_sym) if grad_sym else None

        def cb_fwd(*vals):
            # pure_callback: host round trip for THIS op only; shapes are
            # static so the result spec is the first input's
            spec_out = jax.ShapeDtypeStruct(vals[0].shape, jnp.float32)
            return jax.pure_callback(host_fwd, spec_out, *vals)

        if host_bwd is None:
            core = cb_fwd
        else:
            @jax.custom_vjp
            def core(*vals):
                return cb_fwd(*vals)

            def core_f(*vals):
                return cb_fwd(*vals), vals

            def core_b(res, g):
                specs = tuple(jax.ShapeDtypeStruct(v.shape, jnp.float32)
                              for v in res)
                return jax.pure_callback(host_bwd, specs, g, *res)

            core.defvjp(core_f, core_b)

        def op(*tensors, name=None):
            return apply(f"custom.{py_name}",
                         lambda *vs: core(*[v.astype(jnp.float32)
                                            for v in vs]),
                         *tensors)

        op.__name__ = py_name
        op.__doc__ = (f"Custom C++ op `{sym}` from {self._so_path} "
                      f"(host kernel via pure_callback; "
                      f"grad={'yes' if grad_sym else 'no'}).")
        return op


def load(name, sources, functions=None, extra_cflags=None, verbose=False,
         **kwargs) -> CustomOpLibrary:
    """Compile `sources` (paths or inline C++ strings) and register the
    exported kernels as paddle ops. See module docstring for the C ABI.

    functions: {py_name: {"symbol": str, "grad_symbol": str|None,
                          "n_inputs": int}}
    """
    if not functions:
        raise ValueError(
            "functions= is required: {py_name: {'symbol': ..., "
            "'grad_symbol': ..., 'n_inputs': ...}} — the ctypes ABI has "
            "no self-describing registry (pybind11 is unavailable here)")
    with _lock:
        so = _compile(name, sources, extra_cflags, verbose)
    return CustomOpLibrary(name, so, functions)
