"""Native runtime tier: C++ components behind ctypes.

Role parity: where the reference's runtime is C++ (shared-memory DataLoader
transport, TCPStore rendezvous), so is ours. The library builds lazily from
`src/` with g++ on first use and is cached under `_build/`.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD, "libpaddle_tpu_native.so")
_lock = threading.Lock()
_lib = None


_SRCS = ("shm_ring.cc", "tcp_store.cc")
_HASH_FILE = os.path.join(_BUILD, ".srchash")


def _src_hash():
    import hashlib

    h = hashlib.sha256()
    for f in _SRCS:
        with open(os.path.join(_HERE, "src", f), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()


def _compile():
    # N launcher ranks may hit a cold build dir at once: serialize across
    # processes with an fcntl lock and publish via atomic rename so no
    # process ever CDLLs a half-written .so
    import fcntl

    os.makedirs(_BUILD, exist_ok=True)
    with open(os.path.join(_BUILD, ".buildlock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if not _stale():  # another process built it while we waited
                return
            tmp = f"{_SO}.tmp.{os.getpid()}"
            srcs = [os.path.join(_HERE, "src", f) for f in _SRCS]
            cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                   "-pthread", "-o", tmp] + srcs + ["-lrt"]
            subprocess.run(cmd, check=True, capture_output=True)
            os.rename(tmp, _SO)
            with open(_HASH_FILE + ".tmp", "w") as fh:
                fh.write(_src_hash())
            os.rename(_HASH_FILE + ".tmp", _HASH_FILE)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def _stale():
    # content hash, not mtime: a fresh clone gives src/ and any cached .so
    # near-identical mtimes, and the binary is never committed
    if not os.path.exists(_SO) or not os.path.exists(_HASH_FILE):
        return True
    with open(_HASH_FILE) as fh:
        return fh.read().strip() != _src_hash()


def load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _stale():
            _compile()
        lib = ctypes.CDLL(_SO)
        # shm ring
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint64]
        lib.shm_ring_attach.restype = ctypes.c_void_p
        lib.shm_ring_attach.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_double]
        lib.shm_ring_pop.restype = ctypes.c_int64
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_double]
        lib.shm_ring_slot_size.restype = ctypes.c_uint64
        lib.shm_ring_slot_size.argtypes = [ctypes.c_void_p]
        lib.shm_ring_count.restype = ctypes.c_uint64
        lib.shm_ring_count.argtypes = [ctypes.c_void_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        lib.shm_ring_detach.argtypes = [ctypes.c_void_p]
        lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
        # tcp store
        lib.tcp_store_server_start.restype = ctypes.c_void_p
        lib.tcp_store_server_start.argtypes = [ctypes.c_uint16]
        lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcp_store_connect.restype = ctypes.c_int
        lib.tcp_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                          ctypes.c_double]
        lib.tcp_store_set.restype = ctypes.c_int64
        lib.tcp_store_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_char_p,
                                      ctypes.c_uint64]
        lib.tcp_store_get.restype = ctypes.c_int64
        lib.tcp_store_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_char_p,
                                      ctypes.c_uint64]
        lib.tcp_store_add.restype = ctypes.c_int64
        lib.tcp_store_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_int64]
        lib.tcp_store_check.restype = ctypes.c_int64
        lib.tcp_store_check.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                        ctypes.c_uint32]
        lib.tcp_store_disconnect.argtypes = [ctypes.c_int]
        _lib = lib
        return _lib
