"""Build + ctypes harness for the C inference API (src/capi.cc).

Role parity: `paddle/fluid/inference/capi_exp/` — the C ABI a C/Go
deployment links against. The library is built lazily with g++ (like the
rest of the native tier) and embeds CPython; inside an existing Python
process (tests) the embedded-interpreter path short-circuits and the calls
ride the host interpreter's GIL.

C consumers: include `src/paddle_tpu_capi.h`, link `libpaddle_tpu_capi.so`
and libpython, set PYTHONPATH to reach `paddle_tpu`.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD, "libpaddle_tpu_capi.so")
_HASH_FILE = os.path.join(_BUILD, ".capi.srchash")
_SRCS = ("capi.cc", "paddle_tpu_capi.h")
_lock = threading.Lock()
_lib = None

PD_MAX_NDIM = 8


class PD_TensorData(ctypes.Structure):
    _fields_ = [
        ("dtype", ctypes.c_int32),
        ("ndim", ctypes.c_int32),
        ("shape", ctypes.c_int64 * PD_MAX_NDIM),
        ("data", ctypes.c_void_p),
        ("nbytes", ctypes.c_int64),
    ]


def _src_hash():
    h = hashlib.sha256()
    for f in _SRCS:
        with open(os.path.join(_HERE, "src", f), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()


def _stale():
    if not os.path.exists(_SO) or not os.path.exists(_HASH_FILE):
        return True
    with open(_HASH_FILE) as fh:
        return fh.read().strip() != _src_hash()


def _python_link_flags():
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags.append(f"-lpython{ver}")
    return flags


def _compile():
    import fcntl

    os.makedirs(_BUILD, exist_ok=True)
    with open(os.path.join(_BUILD, ".capi.buildlock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if not _stale():
                return
            tmp = f"{_SO}.tmp.{os.getpid()}"
            cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                    "-pthread", "-o", tmp,
                    os.path.join(_HERE, "src", "capi.cc"),
                    f"-I{os.path.join(_HERE, 'src')}"]
                   + _python_link_flags())
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.rename(tmp, _SO)
            with open(_HASH_FILE + ".tmp", "w") as fh:
                fh.write(_src_hash())
            os.rename(_HASH_FILE + ".tmp", _HASH_FILE)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def load():
    """Build (if stale) and load the C API with typed ctypes signatures."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _stale():
            _compile()
        lib = ctypes.CDLL(_SO)
        lib.PD_PredictorCreate.restype = ctypes.c_int
        lib.PD_PredictorCreate.argtypes = [ctypes.c_char_p]
        for f in (lib.PD_PredictorInputNum, lib.PD_PredictorOutputNum,
                  lib.PD_PredictorDestroy):
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_int]
        for f in (lib.PD_PredictorInputName, lib.PD_PredictorOutputName):
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
                          ctypes.c_size_t]
        lib.PD_PredictorRun.restype = ctypes.c_int
        lib.PD_PredictorRun.argtypes = [
            ctypes.c_int, ctypes.POINTER(PD_TensorData), ctypes.c_int,
            ctypes.POINTER(PD_TensorData), ctypes.c_int]
        lib.PD_ReleaseOutputs.restype = None
        lib.PD_ReleaseOutputs.argtypes = [ctypes.POINTER(PD_TensorData),
                                          ctypes.c_int]
        lib.PD_LastError.restype = ctypes.c_char_p
        lib.PD_LastError.argtypes = []
        _lib = lib
        return _lib


_NP_CODES = {"float32": 0, "int64": 1, "int32": 2, "uint8": 3, "int8": 4,
             "float16": 5, "bfloat16": 6, "bool": 7}


def np_to_td(arr):
    """Pack a numpy array into a PD_TensorData (keeps a ref to the bytes —
    hold the return value alive for the duration of the call)."""
    import numpy as np

    arr = np.ascontiguousarray(arr)
    code = _NP_CODES.get(arr.dtype.name)
    if code is None:
        raise TypeError(f"unsupported dtype {arr.dtype}")
    td = PD_TensorData()
    td.dtype = code
    td.ndim = arr.ndim
    for i, s in enumerate(arr.shape):
        td.shape[i] = s
    buf = arr.tobytes()
    td.data = ctypes.cast(ctypes.create_string_buffer(buf, len(buf)),
                          ctypes.c_void_p)
    td.nbytes = len(buf)
    return td


def td_to_np(td):
    """Copy a PD_TensorData (filled by PD_PredictorRun) into numpy."""
    import numpy as np

    inv = {v: k for k, v in _NP_CODES.items()}
    name = inv[int(td.dtype)]
    if name == "bfloat16":
        import jax.numpy as jnp

        dt = np.dtype(jnp.bfloat16)
    else:
        dt = np.dtype(name)
    raw = ctypes.string_at(td.data, td.nbytes)
    shape = tuple(td.shape[i] for i in range(td.ndim))
    return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
