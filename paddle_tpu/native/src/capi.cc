// C inference API implementation: embeds CPython and drives
// paddle_tpu.inference.capi_bridge.
//
// Role parity: paddle/fluid/inference/capi_exp/pd_inference_api.cc — the
// reference's C API wraps its C++ AnalysisPredictor; here the predictor IS
// an AOT XLA program reachable through Python, so the C ABI layer's job is
// interpreter lifecycle + GIL discipline + buffer marshalling (PyBytes in,
// malloc'd C buffers out). No NumPy C API dependency: the bridge speaks
// (bytes, shape, dtype-code) triples.
//
// Works both embedded in a C program (initializes the interpreter on first
// use, then releases the GIL so any thread can call in) and loaded inside
// an existing Python process via ctypes (Py_IsInitialized short-circuits).

#include "paddle_tpu_capi.h"

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

// Ensure the interpreter exists. When this library bootstraps the
// interpreter itself (pure C host), the bootstrapping thread releases the
// GIL afterwards so that every API call can use PyGILState_Ensure
// uniformly regardless of calling thread.
bool ensure_interpreter() {
  if (Py_IsInitialized()) return true;
  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  config.install_signal_handlers = 0;
  PyStatus status = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(status)) {
    set_error("failed to initialize embedded Python");
    return false;
  }
  PyEval_SaveThread();  // release the GIL taken by initialization
  return true;
}

class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *bridge() {
  static PyObject *mod = nullptr;  // GIL-protected
  if (mod == nullptr) {
    mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (mod == nullptr) set_error_from_python();
  }
  return mod;
}

// call bridge.<fn>(args...); returns new ref or nullptr (error set)
PyObject *bridge_call(const char *fn, PyObject *args) {
  PyObject *mod = bridge();
  if (mod == nullptr) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) set_error_from_python();
  return r;
}

int bridge_call_int(const char *fn, PyObject *args) {
  PyObject *r = bridge_call(fn, args);
  if (r == nullptr) return -1;
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  if (v == -1 && PyErr_Occurred()) {
    set_error_from_python();
    return -1;
  }
  return static_cast<int>(v);
}

int io_name_impl(int handle, int is_input, int idx, char *buf,
                 size_t buflen) {
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  PyObject *r = bridge_call(
      "io_name", Py_BuildValue("(iii)", handle, is_input, idx));
  if (r == nullptr) return -1;
  Py_ssize_t len = 0;
  const char *s = PyUnicode_AsUTF8AndSize(r, &len);
  if (s == nullptr) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  if (buf != nullptr && buflen > 0) {
    size_t n = static_cast<size_t>(len) < buflen - 1
                   ? static_cast<size_t>(len)
                   : buflen - 1;
    std::memcpy(buf, s, n);
    buf[n] = '\0';
  }
  Py_DECREF(r);
  return static_cast<int>(len);
}

}  // namespace

extern "C" {

const char *PD_LastError(void) { return g_last_error.c_str(); }

int PD_PredictorCreate(const char *path_prefix) {
  if (path_prefix == nullptr) {
    set_error("path_prefix is NULL");
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  return bridge_call_int("create", Py_BuildValue("(s)", path_prefix));
}

int PD_PredictorInputNum(int handle) {
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  return bridge_call_int("input_num", Py_BuildValue("(i)", handle));
}

int PD_PredictorOutputNum(int handle) {
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  return bridge_call_int("output_num", Py_BuildValue("(i)", handle));
}

int PD_PredictorInputName(int handle, int idx, char *buf, size_t buflen) {
  return io_name_impl(handle, 1, idx, buf, buflen);
}

int PD_PredictorOutputName(int handle, int idx, char *buf, size_t buflen) {
  return io_name_impl(handle, 0, idx, buf, buflen);
}

int PD_PredictorRun(int handle, const PD_TensorData *inputs, int n_in,
                    PD_TensorData *outputs, int max_out) {
  if (n_in < 0 || (n_in > 0 && inputs == nullptr)) {
    set_error("bad inputs");
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GilGuard gil;

  PyObject *in_list = PyList_New(n_in);
  if (in_list == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < n_in; ++i) {
    const PD_TensorData &t = inputs[i];
    if (t.ndim < 0 || t.ndim > PD_MAX_NDIM || t.data == nullptr) {
      Py_DECREF(in_list);
      set_error("bad input tensor " + std::to_string(i));
      return -1;
    }
    PyObject *shape = PyTuple_New(t.ndim);
    for (int d = 0; d < t.ndim; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
    PyObject *bytes = PyBytes_FromStringAndSize(
        static_cast<const char *>(t.data), t.nbytes);
    PyObject *triple =
        Py_BuildValue("(NNi)", bytes, shape, static_cast<int>(t.dtype));
    PyList_SET_ITEM(in_list, i, triple);  // steals
  }

  PyObject *r =
      bridge_call("run", Py_BuildValue("(iN)", handle, in_list));
  if (r == nullptr) return -1;

  int n_out = static_cast<int>(PyList_Size(r));
  if (n_out > max_out) {
    // never hand back a count the caller can't release safely
    Py_DECREF(r);
    set_error("model produces " + std::to_string(n_out) +
              " outputs but max_out is " + std::to_string(max_out));
    return -1;
  }
  int filled = n_out;
  for (int i = 0; i < filled; ++i) {
    PyObject *triple = PyList_GetItem(r, i);  // borrowed
    PyObject *bytes = PyTuple_GetItem(triple, 0);
    PyObject *shape = PyTuple_GetItem(triple, 1);
    PyObject *code = PyTuple_GetItem(triple, 2);
    PD_TensorData &o = outputs[i];
    std::memset(&o, 0, sizeof(o));
    o.dtype = static_cast<int32_t>(PyLong_AsLong(code));
    o.ndim = static_cast<int32_t>(PyTuple_Size(shape));
    if (o.ndim > PD_MAX_NDIM) {
      // fail like the input-side check: a truncated shape array with a
      // larger ndim would let the caller read past the fixed array
      for (int j = 0; j < i; ++j) std::free(outputs[j].data);
      Py_DECREF(r);
      set_error("output " + std::to_string(i) + " rank " +
                std::to_string(o.ndim) + " exceeds PD_MAX_NDIM");
      return -1;
    }
    for (int d = 0; d < o.ndim; ++d)
      o.shape[d] = PyLong_AsLongLong(PyTuple_GetItem(shape, d));
    char *src = nullptr;
    Py_ssize_t nbytes = 0;
    PyBytes_AsStringAndSize(bytes, &src, &nbytes);
    o.nbytes = static_cast<int64_t>(nbytes);
    o.data = std::malloc(nbytes > 0 ? nbytes : 1);
    if (o.data == nullptr) {
      for (int j = 0; j < i; ++j) std::free(outputs[j].data);
      Py_DECREF(r);
      set_error("out of memory");
      return -1;
    }
    std::memcpy(o.data, src, nbytes);
  }
  Py_DECREF(r);
  if (PyErr_Occurred()) {
    for (int j = 0; j < filled; ++j) std::free(outputs[j].data);
    set_error_from_python();
    return -1;
  }
  return n_out;
}

void PD_ReleaseOutputs(PD_TensorData *outputs, int n) {
  if (outputs == nullptr) return;
  for (int i = 0; i < n; ++i) {
    std::free(outputs[i].data);
    outputs[i].data = nullptr;
    outputs[i].nbytes = 0;
  }
}

int PD_PredictorDestroy(int handle) {
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  return bridge_call_int("destroy", Py_BuildValue("(i)", handle));
}

}  // extern "C"
