/* paddle_tpu C inference API.
 *
 * Role parity: paddle/fluid/inference/capi_exp/pd_inference_api.h — the
 * reference exposes its AnalysisPredictor to C (and Go) via a stable C
 * ABI; this header exposes the paddle_tpu AOT XLA predictor the same way.
 * The implementation (capi.cc) embeds CPython and drives
 * paddle_tpu.inference.capi_bridge; a C program only needs this header,
 * libpaddle_tpu_capi.so, and PYTHONPATH pointing at the package.
 *
 * All functions are thread-safe (the implementation takes the GIL).
 * Errors: functions returning int use >=0 success / <0 failure; the
 * message for the most recent failure on the calling thread is available
 * via PD_LastError().
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* dtype codes — match paddle_tpu.inference.DataType */
enum PD_DataType {
  PD_FLOAT32 = 0,
  PD_INT64 = 1,
  PD_INT32 = 2,
  PD_UINT8 = 3,
  PD_INT8 = 4,
  PD_FLOAT16 = 5,
  PD_BFLOAT16 = 6,
  PD_BOOL = 7,
};

#define PD_MAX_NDIM 8

/* A host tensor. For inputs the caller owns `data`; for outputs filled by
 * PD_PredictorRun the library mallocs `data` — release the batch with
 * PD_ReleaseOutputs. */
typedef struct {
  int32_t dtype;               /* PD_DataType */
  int32_t ndim;                /* <= PD_MAX_NDIM */
  int64_t shape[PD_MAX_NDIM];
  void *data;
  int64_t nbytes;
} PD_TensorData;

/* Load an inference model saved by paddle_tpu (save_inference_model /
 * jit.save path prefix). Returns a handle > 0, or < 0 on failure. */
int PD_PredictorCreate(const char *path_prefix);

/* Number of feed / fetch tensors, or < 0 on bad handle. */
int PD_PredictorInputNum(int handle);
int PD_PredictorOutputNum(int handle);

/* Copy the idx-th feed/fetch name into buf (NUL-terminated, truncated to
 * buflen). Returns name length or < 0. */
int PD_PredictorInputName(int handle, int idx, char *buf, size_t buflen);
int PD_PredictorOutputName(int handle, int idx, char *buf, size_t buflen);

/* Run the program on n_in inputs (feed order). Fills `outputs` with
 * malloc'd results; returns the number of outputs produced, or < 0 on
 * failure — including when the model produces more than max_out outputs
 * (nothing is filled in that case). */
int PD_PredictorRun(int handle, const PD_TensorData *inputs, int n_in,
                    PD_TensorData *outputs, int max_out);

/* Free the data buffers of `n` outputs filled by PD_PredictorRun. */
void PD_ReleaseOutputs(PD_TensorData *outputs, int n);

/* Destroy a predictor. Returns 0/1, or < 0 on bad handle. */
int PD_PredictorDestroy(int handle);

/* Message for the most recent error on this thread ("" if none). The
 * pointer is valid until the next failing call on the same thread. */
const char *PD_LastError(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
