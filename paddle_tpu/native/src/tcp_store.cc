// TCP key-value store for distributed bootstrap.
//
// Role parity: `TCPStore` (paddle/phi/core/distributed/store/tcp_store.h:121)
// — rank-0 hosts a KV server; clients SET/GET(blocking)/ADD/WAIT; barriers
// are built from ADD+WAIT. This is the rendezvous layer under multi-host
// launch (the jax coordination service covers jax's own needs; this store
// serves framework-level rendezvous, elastic membership, and user code).
//
// Wire format (all little-endian):
//   request : u8 op | u32 klen | key | u64 vlen | value
//   response: i64 status/vlen | value
// Ops: 0=SET 1=GET(block until present) 2=ADD(i64 delta; returns new) 3=DEL
//      4=CHECK (returns 1/0 immediately)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

struct Server {
  int listen_fd = -1;
  std::thread loop;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<char>> kv;
  std::vector<std::thread> handlers;
  std::vector<int> client_fds;  // guarded by mu; shutdown() on stop unblocks
                                // handlers stuck in recv so they can be joined
};

bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void handle_client(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    if (!read_all(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_all(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_all(fd, &key[0], klen)) break;
    uint64_t vlen;
    if (!read_all(fd, &vlen, 8)) break;
    std::vector<char> val(vlen);
    if (vlen && !read_all(fd, val.data(), vlen)) break;

    if (op == 0) {  // SET
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv[key] = std::move(val);
      }
      s->cv.notify_all();
      int64_t ok = 0;
      if (!write_all(fd, &ok, 8)) break;
    } else if (op == 1) {  // GET blocking
      std::vector<char> out;
      {
        std::unique_lock<std::mutex> g(s->mu);
        s->cv.wait(g, [&] {
          return s->stop.load() || s->kv.count(key) > 0;
        });
        if (s->stop.load()) break;
        out = s->kv[key];
      }
      int64_t n = static_cast<int64_t>(out.size());
      if (!write_all(fd, &n, 8)) break;
      if (n && !write_all(fd, out.data(), out.size())) break;
    } else if (op == 2) {  // ADD
      int64_t delta = 0;
      if (vlen == 8) memcpy(&delta, val.data(), 8);
      int64_t cur = 0;
      {
        std::lock_guard<std::mutex> g(s->mu);
        auto it = s->kv.find(key);
        if (it != s->kv.end() && it->second.size() == 8) {
          memcpy(&cur, it->second.data(), 8);
        }
        cur += delta;
        std::vector<char> nv(8);
        memcpy(nv.data(), &cur, 8);
        s->kv[key] = std::move(nv);
      }
      s->cv.notify_all();
      if (!write_all(fd, &cur, 8)) break;
    } else if (op == 3) {  // DEL
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv.erase(key);
      }
      int64_t ok = 0;
      if (!write_all(fd, &ok, 8)) break;
    } else if (op == 4) {  // CHECK
      int64_t present;
      {
        std::lock_guard<std::mutex> g(s->mu);
        present = s->kv.count(key) ? 1 : 0;
      }
      if (!write_all(fd, &present, 8)) break;
    } else {
      break;
    }
  }
  {
    // deregister before close: fd numbers get reused by the process, and
    // server_stop must never shutdown() an unrelated descriptor
    std::lock_guard<std::mutex> g(s->mu);
    for (auto it = s->client_fds.begin(); it != s->client_fds.end(); ++it) {
      if (*it == fd) {
        s->client_fds.erase(it);
        break;
      }
    }
  }
  close(fd);
}

}  // namespace

extern "C" {

void* tcp_store_server_start(uint16_t port) {
  Server* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->loop = std::thread([s] {
    while (!s->stop.load()) {
      int fd = accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->client_fds.push_back(fd);
      }
      s->handlers.emplace_back(handle_client, s, fd);
    }
  });
  return s;
}

void tcp_store_server_stop(void* handle) {
  Server* s = static_cast<Server*>(handle);
  s->stop.store(true);
  s->cv.notify_all();
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  if (s->loop.joinable()) s->loop.join();
  {
    // unblock handlers stuck in recv(); they close their own fds on exit
    std::lock_guard<std::mutex> g(s->mu);
    for (int fd : s->client_fds) shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->handlers) {
    if (t.joinable()) t.join();
  }
  delete s;
}

// ---- client ----

int tcp_store_connect(const char* ip, uint16_t port, double timeout_s) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, ip, &addr.sin_addr);
  double waited = 0;
  for (;;) {
    // a stream socket is in unspecified state after a failed connect();
    // every retry needs a fresh fd (POSIX connect(2))
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    close(fd);
    if (waited >= timeout_s) return -1;
    usleep(100000);
    waited += 0.1;
  }
}

static bool send_req(int fd, uint8_t op, const char* key, uint32_t klen,
                     const char* val, uint64_t vlen) {
  if (!write_all(fd, &op, 1)) return false;
  if (!write_all(fd, &klen, 4)) return false;
  if (klen && !write_all(fd, key, klen)) return false;
  if (!write_all(fd, &vlen, 8)) return false;
  if (vlen && !write_all(fd, val, vlen)) return false;
  return true;
}

int64_t tcp_store_set(int fd, const char* key, uint32_t klen,
                      const char* val, uint64_t vlen) {
  if (!send_req(fd, 0, key, klen, val, vlen)) return -1;
  int64_t status;
  return read_all(fd, &status, 8) ? status : -1;
}

// Returns value length; caller buffer must hold it. -1 on error, -3 too small.
int64_t tcp_store_get(int fd, const char* key, uint32_t klen, char* out,
                      uint64_t out_cap) {
  if (!send_req(fd, 1, key, klen, nullptr, 0)) return -1;
  int64_t n;
  if (!read_all(fd, &n, 8)) return -1;
  if (n < 0) return n;
  if (static_cast<uint64_t>(n) > out_cap) {
    std::vector<char> sink(n);
    read_all(fd, sink.data(), n);
    return -3;
  }
  if (n && !read_all(fd, out, static_cast<size_t>(n))) return -1;
  return n;
}

int64_t tcp_store_add(int fd, const char* key, uint32_t klen, int64_t delta) {
  if (!send_req(fd, 2, key, klen, reinterpret_cast<char*>(&delta), 8)) {
    return INT64_MIN;
  }
  int64_t cur;
  return read_all(fd, &cur, 8) ? cur : INT64_MIN;
}

int64_t tcp_store_check(int fd, const char* key, uint32_t klen) {
  if (!send_req(fd, 4, key, klen, nullptr, 0)) return -1;
  int64_t present;
  return read_all(fd, &present, 8) ? present : -1;
}

void tcp_store_disconnect(int fd) { close(fd); }

}  // extern "C"
