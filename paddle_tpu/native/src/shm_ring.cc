// Shared-memory ring buffer for multiprocess DataLoader transport.
//
// Role parity: the reference's DataLoader shared-memory tensor transport
// (paddle/fluid/memory/allocation/mmap_allocator.cc + the C++ blocking queue
// behind create_py_reader_op). Worker processes serialize batches into
// fixed-size slots of a POSIX shm segment; the trainer process pops them
// without touching the Python pickle path under the GIL.
//
// Layout: [Header][slot_size * n_slots]
//   Header: process-shared mutex+conds, head/tail indices, per-slot lengths.
// Blocking push/pop with timeouts; single segment, multiple producers, one
// consumer.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t n_slots;
  uint64_t slot_size;
  uint64_t head;  // next slot to pop
  uint64_t tail;  // next slot to push
  uint64_t count;
  int32_t closed;
  // variable: uint64_t lengths[n_slots];
};

inline uint64_t* slot_lengths(Header* h) {
  return reinterpret_cast<uint64_t*>(h + 1);
}

inline char* slot_data(Header* h, uint64_t idx) {
  char* base = reinterpret_cast<char*>(h + 1) + h->n_slots * sizeof(uint64_t);
  return base + idx * h->slot_size;
}

uint64_t total_bytes(uint64_t n_slots, uint64_t slot_size) {
  return sizeof(Header) + n_slots * sizeof(uint64_t) + n_slots * slot_size;
}

void make_abstime(struct timespec* ts, double timeout_s) {
  clock_gettime(CLOCK_REALTIME, ts);
  time_t sec = static_cast<time_t>(timeout_s);
  long nsec = static_cast<long>((timeout_s - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create a new ring; returns mapped header or nullptr.
void* shm_ring_create(const char* name, uint64_t n_slots,
                      uint64_t slot_size) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t bytes = total_bytes(n_slots, slot_size);
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  memset(h, 0, sizeof(Header));
  h->n_slots = n_slots;
  h->slot_size = slot_size;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  return mem;
}

// Attach to an existing ring.
void* shm_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return mem == MAP_FAILED ? nullptr : mem;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// Push one message. Returns 0 ok, -1 timeout, -2 closed, -3 too large.
int shm_ring_push(void* ring, const char* data, uint64_t len,
                  double timeout_s) {
  Header* h = static_cast<Header*>(ring);
  if (len > h->slot_size) return -3;
  struct timespec ts;
  make_abstime(&ts, timeout_s);
  if (lock_robust(h) != 0) return -1;
  while (h->count == h->n_slots && !h->closed) {
    if (pthread_cond_timedwait(&h->not_full, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint64_t idx = h->tail;
  memcpy(slot_data(h, idx), data, len);
  slot_lengths(h)[idx] = len;
  h->tail = (h->tail + 1) % h->n_slots;
  h->count += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Pop one message into out (cap out_cap). Returns length, -1 timeout,
// -2 closed+empty, -3 buffer too small.
int64_t shm_ring_pop(void* ring, char* out, uint64_t out_cap,
                     double timeout_s) {
  Header* h = static_cast<Header*>(ring);
  struct timespec ts;
  make_abstime(&ts, timeout_s);
  if (lock_robust(h) != 0) return -1;
  while (h->count == 0 && !h->closed) {
    if (pthread_cond_timedwait(&h->not_empty, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->count == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint64_t idx = h->head;
  uint64_t len = slot_lengths(h)[idx];
  if (len > out_cap) {
    pthread_mutex_unlock(&h->mu);
    return -3;
  }
  memcpy(out, slot_data(h, idx), len);
  h->head = (h->head + 1) % h->n_slots;
  h->count -= 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

uint64_t shm_ring_slot_size(void* ring) {
  return static_cast<Header*>(ring)->slot_size;
}

uint64_t shm_ring_count(void* ring) {
  Header* h = static_cast<Header*>(ring);
  return h->count;
}

void shm_ring_close(void* ring) {
  Header* h = static_cast<Header*>(ring);
  if (lock_robust(h) != 0) return;
  h->closed = 1;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mu);
}

void shm_ring_detach(void* ring) {
  Header* h = static_cast<Header*>(ring);
  munmap(ring, total_bytes(h->n_slots, h->slot_size));
}

void shm_ring_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
