"""PyLayer: user-defined autograd functions.

Role parity: `python/paddle/autograd/py_layer.py` + C++
`paddle/fluid/eager/pylayer/`. The user's backward() becomes the vjp of a
hand-wired GradNode in the same grad graph the op dispatcher builds.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.engine import GradNode
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    # paddle also exposes arbitrary attribute stashing on ctx — allowed here
    # by default since this is a plain python object.


class _PyLayerVjp:
    """vjp adapter: flat output cotangents -> user backward -> flat in-grads.

    `wants_tensors` tells the engine to hand over Tensor cotangents directly;
    under create_graph the user's backward ops are recorded so higher-order
    grads flow through PyLayers too."""

    wants_tensors = True

    def __init__(self, cls, ctx, n_diff_inputs, diff_sel):
        self.cls = cls
        self.ctx = ctx
        self.n_diff_inputs = n_diff_inputs
        self.diff_sel = diff_sel  # positions of diff inputs among tensor inputs

    def __call__(self, cots, create_graph=False):
        gts = [Tensor(c) if not isinstance(c, Tensor) else c for c in cots]
        ctx_mgr = flags.enable_grad_guard() if create_graph else \
            flags.no_grad_guard()
        with ctx_mgr:
            out = self.cls.backward(self.ctx, *gts) if len(gts) > 1 else \
                self.cls.backward(self.ctx, gts[0])
        if not isinstance(out, (tuple, list)):
            out = (out,)
        grads = []
        for pos in self.diff_sel:
            g = out[pos] if pos < len(out) else None
            if g is None:
                grads.append(None)
            elif create_graph and isinstance(g, Tensor):
                grads.append(g)
            else:
                grads.append(g._value if isinstance(g, Tensor) else g)
        return tuple(grads)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        track = flags.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        with flags.no_grad_guard():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)

        if track:
            # diff inputs = floating tensor inputs that require grad
            diff_sel = []
            edges = []
            for i, t in enumerate(tensor_inputs):
                if (not t.stop_gradient
                        and jnp.issubdtype(t._value.dtype, np.inexact)):
                    diff_sel.append(i)
                    if t._grad_node is not None:
                        edges.append(("node", t._grad_node[0], t._grad_node[1]))
                    else:
                        edges.append(("leaf", t))
            out_avals = [(tuple(o._value.shape), o._value.dtype) for o in outs]
            node = GradNode(
                cls.__name__,
                _PyLayerVjp(cls, ctx, len(diff_sel), diff_sel),
                edges, len(outs), out_avals)
            for i, o in enumerate(outs):
                if jnp.issubdtype(o._value.dtype, np.inexact):
                    o.stop_gradient = False
                    o._grad_node = (node, i)
        return outs[0] if single else tuple(outs)


class LegacyPyLayer(PyLayer):
    pass
