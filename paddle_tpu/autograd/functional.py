"""Functional higher-order autograd (paddle.incubate.autograd.functional
parity) — thin adapters over jax transforms, which are the TPU-native engine
for jacobians/hessians."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import flags
from ..core.tensor import Tensor


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x) if not isinstance(x, Tensor) else x


def _functionalize(func):
    def f(*vals):
        with flags.trace_guard():
            args = [Tensor(v, stop_gradient=False) for v in vals]
            out = func(*args)
        return _unwrap(out)

    return f


def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    xs_t = [xs] if single else list(xs)
    vals = [t._value for t in xs_t]
    jac = jax.jacobian(_functionalize(func), argnums=tuple(range(len(vals))))(*vals)
    out = _wrap(jac)
    if single:
        return out[0] if isinstance(out, (tuple, list)) else out
    return out


def hessian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    xs_t = [xs] if single else list(xs)
    vals = [t._value for t in xs_t]
    h = jax.hessian(_functionalize(func), argnums=tuple(range(len(vals))))(*vals)
    out = _wrap(h)
    if single:
        while isinstance(out, (tuple, list)):
            out = out[0]
        return out
    return out


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_t = [xs] if single else list(xs)
    vals = [t._value for t in xs_t]
    out, vjp_fn = jax.vjp(_functionalize(func), *vals)
    if v is None:
        cots = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cots = _unwrap(v)
    grads = vjp_fn(cots)
    grads = _wrap(list(grads))
    return _wrap(out), (grads[0] if single else grads)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_t = [xs] if single else list(xs)
    vals = [t._value for t in xs_t]
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        v_t = [v] if isinstance(v, Tensor) else list(v)
        tangents = [t._value for t in v_t]
    out, tang = jax.jvp(_functionalize(func), tuple(vals), tuple(tangents))
    return _wrap(out), _wrap(tang)
