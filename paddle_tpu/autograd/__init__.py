"""paddle.autograd parity (`python/paddle/autograd/`)."""
from ..core.engine import backward, grad  # noqa: F401
from ..core.flags import no_grad_guard as no_grad  # noqa: F401
from ..core.flags import enable_grad_guard as enable_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import jacobian, hessian, vjp, jvp  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "PyLayer",
           "PyLayerContext", "jacobian", "hessian", "vjp", "jvp"]
