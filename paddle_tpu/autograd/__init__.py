"""paddle.autograd parity (`python/paddle/autograd/`)."""
from ..core.engine import backward, grad  # noqa: F401
from ..core.flags import no_grad_guard as no_grad  # noqa: F401
from ..core.flags import enable_grad_guard as enable_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import jacobian, hessian, vjp, jvp  # noqa: F401

__all__ = ["saved_tensors_hooks", "backward", "grad", "no_grad", "enable_grad", "PyLayer",
           "PyLayerContext", "jacobian", "hessian", "vjp", "jvp"]



class saved_tensors_hooks:
    """Reference parity: `paddle.autograd.saved_tensors_hooks` lets users
    pack/unpack activations saved for backward (CPU offload etc.).

    TPU-first gate, documented and LOUD: on this runtime saved residuals
    live inside XLA (jit) or jax-managed vjp closures (eager) — there is
    no host-visible save point to intercept, and the memory lever the
    reference hook serves is `recompute`/`jax.checkpoint` here. Entering
    the context raises with that guidance rather than silently doing
    nothing.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        raise NotImplementedError(
            "saved_tensors_hooks cannot intercept XLA-managed residuals; "
            "use paddle_tpu.distributed.recompute / jax.checkpoint for "
            "activation-memory control")

    def __exit__(self, *a):
        return False
