"""Inferred concurrency structure — the shared model behind Layers 2+5.

Layer 2 (`lock_check`, PT101/PT102) used to infer its guarded-attribute
map privately; Layer 5 (`concurrency_audit`, PT501–PT505) needs the
same facts plus more (which *thread roots* exist, which locks are HELD
at each access, who calls whom).  Both layers now consume ONE model —
built here — so an annotation and the inference can never disagree
silently: there is no second copy of the guard map to drift.

Per class, the model records:

  * **lock attributes** — ``self.X = threading.Lock()/RLock()/
    Condition()/Semaphore()`` (or any lock-named attribute bound to a
    call).  A ``Condition(self._lock)`` built over an existing lock is
    *aliased* to it: holding either name is holding the same mutex, so
    lock identity (PT502/PT504) and "is the cv's own lock held"
    (PT501/PT505) canonicalize through :meth:`ClassModel.canon`.
  * **thread roots** — methods that run on a thread other than the
    constructing one: ``threading.Thread(target=self.m)`` /
    ``Timer(t, self.m)`` targets (including targets reached through a
    callable attribute like ``self._spawner = spawner or self._spawn``),
    nested ``def`` handed to ``Thread(target=...)`` inside a method,
    ``run()`` of a ``Thread`` subclass, and ``do_GET``-style HTTP
    handler methods (each request runs on its own
    ``ThreadingHTTPServer`` thread).
  * **accesses** — every ``self.X`` read/write with the SET of lock
    attributes lexically held (``with self.<lock>:``), per method.
    ``__init__``-family bodies are excluded (construction precedes
    sharing); closures reset the lock context (a closure handed to
    another thread does not inherit the ``with`` that created it) and
    are modeled as pseudo-methods (``m.<locals>.f``).
  * **calls** — same-class ``self.m(...)`` call sites with held locks
    (the one-level interprocedural edge: a private helper whose every
    internal call site holds lock L is analyzed as if its body ran
    under L), cross-object ``self.attr.m(...)`` sites (PT502's
    cross-class acquisition edges), and raw calls with enough shape
    (dotted name, receiver attribute, timeout-arg presence) for the
    blocking-call classifier.

The model is stdlib-`ast` only and never imports the analyzed code.
"""
from __future__ import annotations

import ast

__all__ = [
    "Access", "CallSite", "ExtCall", "RawCall", "Acquire",
    "MethodModel", "ClassModel", "FileModel", "build_file_model",
    "apply_presumed_locks",
    "LOCK_CTORS", "THREADSAFE_CTORS", "SKIP_METHODS", "MUTATORS",
]

LOCK_CTORS = {"Lock": "lock", "RLock": "lock", "Condition": "cond",
              "Semaphore": "sema", "BoundedSemaphore": "sema"}
# attributes holding these ctors are internally synchronized — calling
# set()/clear()/put() on an Event/Queue needs no external lock
THREADSAFE_CTORS = {"Event", "Queue", "SimpleQueue", "LifoQueue",
                    "PriorityQueue", "local", "Barrier"}
SKIP_METHODS = {"__init__", "__new__", "__del__", "__init_subclass__"}
# method calls that mutate their receiver: `self._events.append(x)` is
# a WRITE to _events, same as subscript assignment
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "update", "add",
    "discard", "setdefault", "sort", "reverse", "move_to_end",
}
# HTTP-handler entry points: ThreadingHTTPServer runs each request's
# handler on its own thread, so every do_* method is a thread root
_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_DELETE",
                    "do_HEAD", "do_PATCH", "do_OPTIONS", "handle",
                    "handle_one_request"}
_THREAD_CTORS = {"Thread", "Timer"}
# calls that hand their callable argument to a foreign thread (or an
# async signal context).  A bound method passed to anything ELSE —
# sorted(key=self.rank), map(self.f, xs) — runs synchronously and is
# NOT a thread root.
_HANDOFF_CALLS = {"submit", "add_done_callback", "start_new_thread",
                  "signal", "run_in_executor", "spawn_thread"}
_PROPERTY_DECOS = {"property", "cached_property"}


def dotted(node) -> str:
    """'a.b.c' for a Name/Attribute chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def self_attr(node):
    """'X' when node is `self.X`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def lock_name_like(name: str) -> bool:
    low = name.lower()
    return "lock" in low or low.endswith(("_cv", "_cond", "_mutex"))


def is_lock_ctor(node) -> bool:
    return isinstance(node, ast.Call) and \
        dotted(node.func).rsplit(".", 1)[-1] in LOCK_CTORS


class Access:
    """One `self.X` read or write; `locks` is the frozenset of lock
    attribute names (canonicalized) lexically held at the site."""

    __slots__ = ("attr", "write", "locks", "line", "method")

    def __init__(self, attr, write, locks, line, method):
        self.attr = attr
        self.write = bool(write)
        self.locks = frozenset(locks)
        self.line = int(line)
        self.method = method

    @property
    def locked(self) -> bool:
        return bool(self.locks)


class CallSite:
    """`self.m(...)` — a same-class method call with held locks."""

    __slots__ = ("callee", "locks", "line", "method")

    def __init__(self, callee, locks, line, method):
        self.callee = callee
        self.locks = frozenset(locks)
        self.line = int(line)
        self.method = method


class ExtCall:
    """`self.attr.m(...)` — a call into another object held in an
    attribute; PT502 resolves `attr`'s class through the project model
    to build cross-class lock-acquisition edges."""

    __slots__ = ("attr", "meth", "locks", "line", "method")

    def __init__(self, attr, meth, locks, line, method):
        self.attr = attr
        self.meth = meth
        self.locks = frozenset(locks)
        self.line = int(line)
        self.method = method


class RawCall:
    """Any call, with enough shape for the blocking classifier:
    `name` is the full dotted callee ('' for computed callees),
    `recv_attr` is 'X' when the receiver is `self.X`, `tail` the final
    component, `has_args`/`has_timeout` describe the argument list."""

    __slots__ = ("name", "recv_attr", "tail", "locks", "line", "method",
                 "has_args", "has_timeout")

    def __init__(self, name, recv_attr, tail, locks, line, method,
                 has_args, has_timeout):
        self.name = name
        self.recv_attr = recv_attr
        self.tail = tail
        self.locks = frozenset(locks)
        self.line = int(line)
        self.method = method
        self.has_args = bool(has_args)
        self.has_timeout = bool(has_timeout)


class Acquire:
    """One `with self.<lock>:` entry: the lock taken and the locks
    already held — the PT502 acquisition-order edge."""

    __slots__ = ("lock", "held", "line", "method")

    def __init__(self, lock, held, line, method):
        self.lock = lock
        self.held = frozenset(held)
        self.line = int(line)
        self.method = method


class MethodModel:
    __slots__ = ("name", "lineno", "accesses", "calls", "ext_calls",
                 "raw_calls", "acquires", "is_pseudo")

    def __init__(self, name, lineno, is_pseudo=False):
        self.name = name
        self.lineno = int(lineno)
        self.accesses: list = []
        self.calls: list = []
        self.ext_calls: list = []
        self.raw_calls: list = []
        self.acquires: list = []
        self.is_pseudo = bool(is_pseudo)  # closure pseudo-method


class ClassModel:
    """The inferred concurrency structure of one class."""

    __slots__ = ("name", "file", "lineno", "locks", "cond_alias",
                 "threadsafe", "methods", "attr_types", "callable_attrs",
                 "thread_roots", "bases", "properties", "presumed",
                 "construction_only")

    def __init__(self, name, file, lineno):
        self.name = name
        self.file = file
        self.lineno = int(lineno)
        self.locks: dict = {}          # attr -> kind (lock/cond/sema)
        self.cond_alias: dict = {}     # cond attr -> underlying lock attr
        self.threadsafe: set = set()
        self.methods: dict = {}        # name -> MethodModel
        self.attr_types: dict = {}     # attr -> class name (self.x = C())
        self.callable_attrs: dict = {} # attr -> {method names it may call}
        self.thread_roots: dict = {}   # method name -> reason
        self.bases: list = []
        self.properties: set = set()   # @property methods (reads, not
                                       # bound-method escapes)
        self.presumed: dict = {}       # method -> frozenset of locks the
                                       # repo's conventions say callers
                                       # hold (see apply_presumed_locks)
        self.construction_only: set = set()  # private helpers called
                                       # ONLY from __init__ — their
                                       # accesses precede sharing, like
                                       # __init__'s own

    def canon(self, lock: str) -> str:
        """Canonical lock identity: a Condition built over an existing
        lock IS that lock (holding either is holding the same mutex)."""
        return self.cond_alias.get(lock, lock)

    def canon_set(self, locks) -> frozenset:
        return frozenset(self.canon(x) for x in locks)

    def holds(self, locks, lock: str) -> bool:
        """Is `lock` (by identity, through cv aliasing) held?"""
        return self.canon(lock) in self.canon_set(locks)

    # ---- interprocedural (one level): call-site lock propagation ----
    def call_sites_of(self, name):
        """All same-class call sites of method `name` (every method's
        body, including pseudo-methods)."""
        sites = []
        for m in self.methods.values():
            for c in m.calls:
                if c.callee == name:
                    sites.append(c)
        return sites

    def propagated_locks(self, name) -> frozenset:
        """Locks a PRIVATE helper can assume held: the intersection of
        the locks held at its internal call sites, when every site
        holds at least one lock and nothing else can reach it (public
        name or thread root ⇒ no assumption).  One level only — the
        call sites' own lexical locks, not their callers'."""
        if not name.startswith("_") or name.startswith("__") \
                or name in self.thread_roots:
            return frozenset()
        sites = self.call_sites_of(name)
        if not sites:
            return frozenset()
        held = None
        for c in sites:
            locks = self.canon_set(c.locks)
            if not locks:
                return frozenset()
            held = locks if held is None else (held & locks)
        return held or frozenset()

    def effective_locks(self, method: MethodModel, access) -> frozenset:
        """Lexical locks at the access plus the helper's propagated
        call-site locks plus whatever the repo's conventions presume
        callers hold (`*_locked` suffix / def-level ok[PT101] claim)."""
        return self.canon_set(access.locks) | \
            self.propagated_locks(method.name) | \
            self.presumed.get(method.name, frozenset())

    def held_at(self, method_name: str, locks) -> frozenset:
        """Canonical held set at a call/access site: lexical locks plus
        the containing method's propagated + presumed locks."""
        return self.canon_set(locks) | \
            self.propagated_locks(method_name) | \
            self.presumed.get(method_name, frozenset())


class FileModel:
    __slots__ = ("path", "tree", "classes")

    def __init__(self, path, tree, classes):
        self.path = path
        self.tree = tree
        self.classes = classes  # list[ClassModel], source order


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def _call_has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _thread_target(call: ast.Call):
    """The target/callback expression of a Thread/Timer ctor, or None."""
    tail = dotted(call.func).rsplit(".", 1)[-1]
    if tail not in _THREAD_CTORS:
        return None
    for kw in call.keywords:
        if kw.arg in ("target", "function"):
            return kw.value
    if tail == "Timer" and len(call.args) >= 2:
        return call.args[1]
    if tail == "Thread" and call.args:
        # Thread(group, target, ...) — positional target is arg 1;
        # nobody passes group positionally, so treat arg 0 as target
        # only if it is not None
        a = call.args[0]
        if not (isinstance(a, ast.Constant) and a.value is None):
            return a
        if len(call.args) >= 2:
            return call.args[1]
    return None


class _MethodScanner:
    """Walk one method body collecting the model facts.  `locks` in
    every record is the RAW attribute-name set; canonicalization (cv
    aliasing) happens at query time on the ClassModel."""

    def __init__(self, cls: ClassModel, meth: MethodModel,
                 pseudo_out: list):
        self.cls = cls
        self.m = meth
        self.pseudo_out = pseudo_out  # (name, FunctionDef) closures
        self._local_targets: set = set()  # nested defs passed to Thread

    def scan(self, fn):
        for stmt in fn.body:
            self._walk(stmt, frozenset(), fn)
        return self._local_targets

    # -- helpers --
    def _with_locks(self, stmt: ast.With):
        held = set()
        for item in stmt.items:
            attr = self_attr(item.context_expr)
            if attr is None:
                continue
            if attr in self.cls.locks:
                held.add(attr)
            elif lock_name_like(attr):
                # `with self._lock:` where the lock is defined in a
                # base class — register it on first use (the ctor scan
                # only sees this class's body)
                self.cls.locks[attr] = "lock"
                held.add(attr)
        return held

    def _record_call(self, node: ast.Call, locks):
        name = dotted(node.func)
        tail = name.rsplit(".", 1)[-1] if name else ""
        recv_attr = None
        callee_attr = self_attr(node.func)
        if isinstance(node.func, ast.Attribute):
            recv_attr = self_attr(node.func.value)
        if callee_attr is not None and callee_attr in self.cls.methods:
            self.m.calls.append(CallSite(callee_attr, locks,
                                         node.lineno, self.m.name))
        elif callee_attr is not None and \
                callee_attr in self.cls.callable_attrs:
            # self._spawner(...) where _spawner may be a bound method:
            # a call site for every method it can name
            for target in self.cls.callable_attrs[callee_attr]:
                self.m.calls.append(CallSite(target, locks,
                                             node.lineno, self.m.name))
        elif recv_attr is not None and recv_attr not in self.cls.locks:
            self.m.ext_calls.append(ExtCall(recv_attr, tail, locks,
                                            node.lineno, self.m.name))
        self.m.raw_calls.append(RawCall(
            name, recv_attr, tail, locks, node.lineno, self.m.name,
            has_args=bool(node.args),
            has_timeout=_call_has_timeout(node)))
        # Thread(target=self.X or nested def) discovered anywhere;
        # non-method names resolve at finalize (they are pruned there)
        target = _thread_target(node)
        if target is not None:
            t_attr = self_attr(target)
            if t_attr is not None:
                self.cls.thread_roots.setdefault(
                    t_attr, "Thread/Timer target")
            elif isinstance(target, ast.Name):
                self._local_targets.add(target.id)
        elif tail in _HANDOFF_CALLS and (node.args or node.keywords):
            # a bound method handed to a thread-handoff callable
            # (executor.submit, signal.signal) runs on a foreign
            # thread/async context.  Property reads passed as plain
            # values (sorted(key=...), range(self.ndim)) do not.
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                a_attr = self_attr(arg)
                if a_attr is not None and a_attr in self.cls.methods \
                        and a_attr not in self.cls.properties:
                    self.cls.thread_roots.setdefault(
                        a_attr, "escaped bound method (callback)")

    def _walk(self, node, locks, fn):
        if isinstance(node, ast.ClassDef):
            # a nested class (the Handler-in-__init__ idiom) is its own
            # ClassModel — its `self` is not ours
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            # a closure does not inherit the lock it was created under;
            # it becomes a pseudo-method (possibly a thread root)
            self.pseudo_out.append((f"{self.m.name}.<locals>."
                                    f"{node.name}", node))
            return
        if isinstance(node, ast.With):
            held = self._with_locks(node)
            for lk in sorted(held):
                if lk not in locks:
                    self.m.acquires.append(Acquire(lk, locks,
                                                   node.lineno,
                                                   self.m.name))
            for item in node.items:
                self._walk(item.context_expr, locks, fn)
            inner = locks | frozenset(held)
            for child in node.body:
                self._walk(child, inner, fn)
            return
        if isinstance(node, ast.Attribute):
            attr = self_attr(node)
            if attr is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                self.m.accesses.append(Access(attr, write, locks,
                                              node.lineno, self.m.name))
            for child in ast.iter_child_nodes(node):
                self._walk(child, locks, fn)
            return
        if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            # self._map[k] = v mutates _map: a write, then the normal
            # walk records the Load of the container
            attr = self_attr(node.value)
            if attr is not None:
                self.m.accesses.append(Access(attr, True, locks,
                                              node.lineno, self.m.name))
        if isinstance(node, ast.Call):
            attr = self_attr(node.func)
            self._record_call(node, locks)
            if attr is not None and (attr in self.cls.methods
                                     or attr in self.cls.callable_attrs):
                # self.method(...) is a call, not state access — skip
                # the func attribute but scan the arguments
                for child in list(node.args) + [kw.value
                                               for kw in node.keywords]:
                    self._walk(child, locks, fn)
                return
            # self._events.append(x): a mutating method on a container
            # attribute is a write to that attribute
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                r_attr = self_attr(node.func.value)
                if r_attr is not None:
                    self.m.accesses.append(Access(r_attr, True, locks,
                                                  node.lineno,
                                                  self.m.name))
        if isinstance(node, ast.AugAssign):
            # x += 1 parses the target as Store only; it is a read AND
            # a write — record both so `self.n += 1` outside the lock
            # is caught as the read-modify-write race it is
            attr = self_attr(node.target)
            if attr is not None:
                self.m.accesses.append(Access(attr, False, locks,
                                              node.lineno, self.m.name))
        for child in ast.iter_child_nodes(node):
            self._walk(child, locks, fn)


def _uncalled_self_refs(node) -> set:
    """`self.X` Load references in `node` that are not the callee of a
    call — a bound method escaping as a value."""
    called = {id(x.func) for x in ast.walk(node)
              if isinstance(x, ast.Call)}
    return {self_attr(x) for x in ast.walk(node)
            if isinstance(x, ast.Attribute) and id(x) not in called
            and isinstance(getattr(x, "ctx", None), ast.Load)
            and self_attr(x)}


def _scan_class_attrs(cls_node: ast.ClassDef, model: ClassModel):
    """First pass: lock/threadsafe/typed/callable attribute discovery
    (anywhere in the class — __init__ included)."""
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        if not any(self_attr(t) is not None for t in node.targets):
            # `other.cb = self.m`: a bound method escaping to a foreign
            # object — it may be invoked from a foreign thread.  Names
            # that are not methods are pruned at finalize.
            for name in _uncalled_self_refs(node.value):
                model.thread_roots.setdefault(
                    name, "escaped bound method (assigned callback)")
            continue
        for t in node.targets:
            attr = self_attr(t)
            if attr is None:
                continue
            v = node.value
            if is_lock_ctor(v):
                ctor = dotted(v.func).rsplit(".", 1)[-1]
                model.locks[attr] = LOCK_CTORS[ctor]
                if ctor == "Condition":
                    # Condition(self._lock): aliased to the real lock
                    under = self_attr(v.args[0]) if v.args else None
                    for kw in v.keywords:
                        if kw.arg == "lock":
                            under = self_attr(kw.value)
                    if under:
                        model.cond_alias[attr] = under
            elif lock_name_like(attr) and isinstance(v, ast.Call):
                model.locks.setdefault(attr, "lock")
            elif isinstance(v, ast.Call):
                ctor = dotted(v.func).rsplit(".", 1)[-1]
                if ctor in THREADSAFE_CTORS:
                    model.threadsafe.add(attr)
                elif ctor and ctor[:1].isupper():
                    model.attr_types[attr] = ctor
            # callable attr: every `self.m` (uncalled bound method)
            # appearing in a non-Call RHS is a method this attr may
            # invoke (`self._spawner = spawner or self._spawn`)
            if not isinstance(v, ast.Call):
                names = {self_attr(x) for x in ast.walk(v)
                         if isinstance(x, ast.Attribute)
                         and isinstance(getattr(x, "ctx", None),
                                        ast.Load)}
                methods = {n for n in names if n}
                if methods:
                    model.callable_attrs.setdefault(attr, set()).update(
                        methods)


def _build_class(cls_node: ast.ClassDef, path: str) -> ClassModel:
    model = ClassModel(cls_node.name, path, cls_node.lineno)
    model.bases = [dotted(b).rsplit(".", 1)[-1] for b in cls_node.bases
                   if dotted(b)]
    method_nodes = [n for n in cls_node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))]
    for fn in method_nodes:
        model.methods[fn.name] = MethodModel(fn.name, fn.lineno)
        for deco in fn.decorator_list:
            if dotted(deco).rsplit(".", 1)[-1] in _PROPERTY_DECOS:
                model.properties.add(fn.name)
    _scan_class_attrs(cls_node, model)
    # callable_attrs may name methods: keep only real ones
    for attr, names in list(model.callable_attrs.items()):
        real = {n for n in names if n in model.methods}
        if real:
            model.callable_attrs[attr] = real
        else:
            del model.callable_attrs[attr]
    # scan bodies (skip the construction family), lifting closures
    # into pseudo-methods with a reset lock context
    pending = []
    init_callees: set = set()
    for fn in method_nodes:
        if fn.name in SKIP_METHODS:
            # still scan __init__ for Thread(target=...) roots and
            # nested closures, but record no accesses from it; KEEP the
            # callee names so init-only helpers can be recognized
            sink = MethodModel(fn.name, fn.lineno)
            sc = _MethodScanner(model, sink, pending)
            local_targets = sc.scan(fn)
            init_callees.update(c.callee for c in sink.calls)
            _resolve_local_targets(model, pending, local_targets)
            continue
        meth = model.methods[fn.name]
        sc = _MethodScanner(model, meth, pending)
        local_targets = sc.scan(fn)
        _resolve_local_targets(model, pending, local_targets)
    while pending:
        name, fn = pending.pop(0)
        pm = MethodModel(name, fn.lineno, is_pseudo=True)
        model.methods[name] = pm
        sc = _MethodScanner(model, pm, pending)
        local_targets = sc.scan(fn)
        _resolve_local_targets(model, pending, local_targets)
    # thread roots: resolve + enrich
    for name in list(model.thread_roots):
        if name not in model.methods or name in model.properties:
            del model.thread_roots[name]  # not a method / a property
            # read that merely LOOKED like a bound-method escape
    if "Thread" in model.bases and "run" in model.methods:
        model.thread_roots.setdefault("run", "Thread subclass run()")
    for name in model.methods:
        if name in _HANDLER_METHODS or (
                name.split(".")[-1] in _HANDLER_METHODS):
            model.thread_roots.setdefault(
                name, "HTTP handler (per-request thread)")
    # a private helper reachable ONLY from __init__ (directly or
    # through other construction-only helpers) runs before the object
    # is shared — its accesses are construction, not races
    changed = True
    while changed:
        changed = False
        for name, meth in model.methods.items():
            if name in model.construction_only or meth.is_pseudo \
                    or not name.startswith("_") \
                    or name in model.thread_roots:
                continue
            sites = model.call_sites_of(name)
            if any(s.method not in model.construction_only
                   for s in sites):
                continue
            if name in init_callees or sites:
                model.construction_only.add(name)
                changed = True
    return model


def _resolve_local_targets(model, pending, local_targets):
    """Nested defs handed to Thread(target=...): by now they sit in
    `pending` under their pseudo-names — mark them as roots."""
    if not local_targets:
        return
    for name, _fn in pending:
        if name.rsplit(".", 1)[-1] in local_targets:
            model.thread_roots.setdefault(name, "Thread target (closure)")
    for name in model.methods:
        if name.rsplit(".", 1)[-1] in local_targets and \
                model.methods[name].is_pseudo:
            model.thread_roots.setdefault(name, "Thread target (closure)")


def apply_presumed_locks(cls: ClassModel, suppressions=None) -> None:
    """Populate ``cls.presumed``: locks a helper may assume held because
    the repo's conventions say callers hold them — a method named
    ``*_locked``, or one whose ``def`` line carries an explicit
    ``# pt-lint: ok[PT101]``/``ok[PT102]`` suppression (the documented
    "callers hold the lock" idiom).  The lock IDENTITY is still
    inferred, never trusted: the intersection of locks actually held at
    the helper's locked call sites, falling back to the class's sole
    mutex when it has exactly one.  Closures (pseudo-methods) inherit
    their parent's presumption unless they are thread roots — a
    sort-key closure built inside a locked helper runs under the lock;
    a closure handed to ``Thread`` does not.

    `suppressions` is duck-typed (``listed_rules(line) -> set``) so this
    module stays importable without :mod:`.report`."""
    sole = {cls.canon(lk) for lk in cls.locks}
    sole_set = frozenset(sole) if len(sole) == 1 else frozenset()

    def claimed(name, m):
        if name.rsplit(".", 1)[-1].endswith("_locked"):
            return True
        if suppressions is not None and not m.is_pseudo:
            return bool(suppressions.guard_claims(m.lineno)
                        & {"PT101", "PT102"})
        return False

    claimers = [name for name, m in cls.methods.items()
                if name not in cls.thread_roots and claimed(name, m)]

    def infer_identity(name):
        # intersect over call sites that hold SOMETHING (lexically or
        # by the caller's own presumption) — the fixpoint lets a claim
        # chain through helpers: step (lock) -> _a (claimed) -> _b
        held = None
        for c in cls.call_sites_of(name):
            locks = cls.canon_set(c.locks) | \
                cls.presumed.get(c.method, frozenset())
            if locks:
                held = locks if held is None else (held & locks)
        return held or sole_set

    for _round in range(len(claimers) + 1):
        changed = False
        for name in claimers:
            new = infer_identity(name)
            if new != cls.presumed.get(name):
                cls.presumed[name] = new
                changed = True
        if not changed:
            break
    # sync closures inherit their parent's presumption (a sort-key
    # closure built inside a locked helper runs under the lock); a
    # closure handed to Thread does not
    for name, m in cls.methods.items():
        if not m.is_pseudo or name in cls.thread_roots \
                or name in cls.presumed:
            continue
        inherited = cls.presumed.get(name.split(".<locals>.", 1)[0])
        if inherited:
            cls.presumed[name] = inherited


def build_file_model(source: str, path: str,
                     tree: ast.Module | None = None) -> FileModel:
    if tree is None:
        tree = ast.parse(source)
    classes = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            classes.append(_build_class(node, path))
    return FileModel(path, tree, classes)
