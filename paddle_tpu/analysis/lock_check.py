"""Layer 2: lock-discipline race checker (rules PT101/PT102).

The threaded modules (observability trace/metrics/flight/step_stats,
resilience watchdog, serving, autotune, elastic) share one idiom: a
``threading.Lock`` guards a small set of mutable attributes, and every
access is supposed to happen inside ``with self._lock:``. The bug class
this catches is the one that bites ring-buffer/span code under the
watchdog thread: a write that *usually* runs on one thread quietly
starts racing when a daemon thread (watchdog poll, serving handler,
heartbeat) touches the same attribute.

The guard map itself — which attributes each lock protects, which
locks are held at each access — is no longer inferred here: it is
PRODUCED by the shared :mod:`.threadmodel` (the same model Layer 5's
``concurrency_audit`` consumes for PT501–PT505), so an annotation and
the inference can never disagree silently.  This module keeps only the
PT101/PT102 *judgment*:

  * guarded set — attributes *written* at least once with a lock
    effectively held anywhere in the class;
  * violations — any access to a guarded attribute with NO lock
    effectively held: PT101 for writes, PT102 for reads.

"Effectively held" is the model's call: lexical ``with self.<lock>:``
scope, plus locks a private helper's every internal call site holds,
plus locks the repo's conventions presume callers hold (a ``*_locked``
name, or a ``def``-line ``# pt-lint: ok[PT101,PT102] (caller holds
_lock)`` guard claim — see ``threadmodel.apply_presumed_locks``).  A
guard claim that inference CONTRADICTS is Layer 5's PT504.

Deliberately excluded: ``__init__``-family bodies and helpers
reachable only from them (construction precedes sharing), the lock
attributes themselves, internally-synchronized Event/Queue attributes,
and calls to the class's own methods (``self.beat()`` is a call, not
state access — the callee's body is analyzed on its own).  Closures
reset the lock context: a closure handed to another thread does NOT
inherit the ``with`` that created it.

The module-level pass for the module-global ``_lock``/``_cache`` idiom
(autotune) still lives here: globals written under a module-level lock
become guarded; functions touching them outside the lock are flagged.
"""
from __future__ import annotations

import ast

from . import threadmodel as tm
from .report import Violation

__all__ = ["analyze_source", "analyze_file", "RULE_IDS"]

RULE_IDS = ("PT101", "PT102")

_LOCK_CTORS = set(tm.LOCK_CTORS)
_MUTATORS = tm.MUTATORS


def _dotted(node) -> str:
    return tm.dotted(node)


def _is_lock_ctor(node) -> bool:
    return tm.is_lock_ctor(node)


class _Access:
    __slots__ = ("attr", "write", "locked", "line", "func")

    def __init__(self, attr, write, locked, line, func):
        self.attr = attr
        self.write = write
        self.locked = locked
        self.line = line
        self.func = func


def _with_locks(stmt: ast.With, lock_names):
    """Module-level lock names among this with-statement's managers."""
    held = set()
    for item in stmt.items:
        expr = item.context_expr
        if isinstance(expr, ast.Name) and expr.id in lock_names:
            held.add(expr.id)
    return held


def _analyze_class(cls: tm.ClassModel, out: list) -> None:
    """PT101/PT102 over one inferred ClassModel."""
    if not cls.locks:
        return
    flat = []  # (attr, write, effectively_locked, line, method)
    for name, meth in cls.methods.items():
        if name in tm.SKIP_METHODS or name in cls.construction_only:
            continue
        for a in meth.accesses:
            if a.attr in cls.locks or a.attr in cls.threadsafe:
                continue
            flat.append((a.attr, a.write,
                         bool(cls.effective_locks(meth, a)),
                         a.line, name))
    guarded = {attr for attr, write, locked, _l, _m in flat
               if write and locked}
    for attr, write, locked, line, meth_name in flat:
        if attr not in guarded or locked:
            continue
        rule = "PT101" if write else "PT102"
        verb = "writes" if write else "reads"
        out.append(Violation(
            cls.file, line, rule,
            f"{cls.name}.{meth_name} {verb} lock-guarded attribute "
            f"`{attr}` outside `with self.<lock>:`"))


def _local_bindings(fn) -> set:
    """Names bound locally in `fn` (params, plain assignments, loop/
    with/except targets) MINUS its `global` declarations — a Name whose
    id is in this set refers to a local, not the module global."""
    declared = {name for node in ast.walk(fn)
                if isinstance(node, ast.Global)
                for name in node.names}
    bound = {a.arg for a in (
        list(fn.args.posonlyargs) + list(fn.args.args)
        + list(fn.args.kwonlyargs))}
    for a in (fn.args.vararg, fn.args.kwarg):
        if a is not None:
            bound.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            bound.add(node.name)
    return bound - declared


def _analyze_module_level(tree: ast.Module, path: str, out: list) -> None:
    """The `_lock = threading.Lock()` + module-global state idiom.

    Candidate globals are the module's top-level assigned names; a
    function's access counts whenever the name is not shadowed by a
    local binding — reads never need a `global` statement, so requiring
    one would make every lock-free read invisible (the exact race class
    this layer exists for)."""
    lock_names = set()
    module_vars = set()
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                lock_names.update(names)
            else:
                module_vars.update(names)
    module_vars -= lock_names
    if not lock_names or not module_vars:
        return
    functions = [n for n in tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    accesses: list = []
    for fn in functions:
        visible = module_vars - _local_bindings(fn)
        declared = {name for node in ast.walk(fn)
                    if isinstance(node, ast.Global)
                    for name in node.names}
        watched = visible | (declared & module_vars)
        if not watched:
            continue

        def walk(node, locked, fn=fn, watched=watched):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return
            if isinstance(node, ast.With):
                held = _with_locks(node, lock_names)
                for child in node.body:
                    walk(child, locked or bool(held))
                return
            if isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)) and isinstance(
                    node.value, ast.Name) and node.value.id in watched:
                accesses.append(_Access(node.value.id, True, locked,
                                        node.lineno, fn.name))
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and isinstance(
                    node.func.value, ast.Name) and \
                    node.func.value.id in watched:
                accesses.append(_Access(node.func.value.id, True,
                                        locked, node.lineno, fn.name))
            if isinstance(node, ast.Name) and node.id in watched:
                accesses.append(_Access(
                    node.id, isinstance(node.ctx, (ast.Store, ast.Del)),
                    locked, node.lineno, fn.name))
            for child in ast.iter_child_nodes(node):
                walk(child, locked)

        for stmt in fn.body:
            walk(stmt, False)
    guarded = {a.attr for a in accesses if a.write and a.locked}
    for a in accesses:
        if a.attr not in guarded or a.locked:
            continue
        rule = "PT101" if a.write else "PT102"
        verb = "writes" if a.write else "reads"
        out.append(Violation(
            path, a.line, rule,
            f"{a.func} {verb} module-lock-guarded global `{a.attr}` "
            f"outside `with <lock>:`"))


def analyze_source(source: str, path: str,
                   tree: ast.Module | None = None,
                   suppressions=None) -> list:
    """PT101/PT102 for one file.  `suppressions` (duck-typed, see
    ``threadmodel.apply_presumed_locks``) feeds def-line guard-claim
    annotations into the presumed-lock inference; without it only the
    ``*_locked`` naming convention establishes a presumption."""
    if tree is None:
        tree = ast.parse(source)
    fm = tm.build_file_model(source, path, tree=tree)
    out: list = []
    for cls in fm.classes:
        tm.apply_presumed_locks(cls, suppressions)
        _analyze_class(cls, out)
    _analyze_module_level(tree, path, out)
    out.sort(key=Violation.sort_key)
    return out


def analyze_file(path: str, rel: str | None = None) -> list:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return analyze_source(source, rel or path)
