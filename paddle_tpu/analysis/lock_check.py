"""Layer 2: lock-discipline race checker (rules PT101/PT102).

The threaded modules (observability trace/metrics/flight/step_stats,
resilience watchdog, serving, autotune, elastic) share one idiom: a
``threading.Lock`` guards a small set of mutable attributes, and every
access is supposed to happen inside ``with self._lock:``. The bug class
this catches is the one that bites ring-buffer/span code under the
watchdog thread: a write that *usually* runs on one thread quietly
starts racing when a daemon thread (watchdog poll, serving handler,
heartbeat) touches the same attribute.

Inference, per class:

  * lock attributes — ``self.X = threading.Lock()/RLock()/Condition()``
    (or any assignment to a name containing "lock"/"cv"/"cond");
  * guarded set — attributes *written* at least once inside a
    ``with self.<lock>:`` body anywhere in the class;
  * violations — any access to a guarded attribute outside a lock body:
    PT101 for writes, PT102 for reads.

Deliberately excluded: ``__init__``/``__del__``/``__new__`` bodies
(construction precedes sharing), the lock attributes themselves, and
calls to the class's own methods (``self.beat()`` is a call, not state
access — the callee's body is analyzed on its own).  Nested functions
reset the lock context: a closure handed to another thread does NOT
inherit the ``with`` that created it.

The same inference runs at module level for the module-global
``_lock``/``_cache`` idiom (autotune): globals written under a
module-level lock become guarded; functions touching them outside the
lock are flagged.  Helpers that are only ever called with the lock held
annotate their ``def`` line with ``# pt-lint: ok[PT101,PT102]``.
"""
from __future__ import annotations

import ast

from .report import Violation

__all__ = ["analyze_source", "analyze_file", "RULE_IDS"]

RULE_IDS = ("PT101", "PT102")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_SKIP_METHODS = {"__init__", "__new__", "__del__", "__init_subclass__"}
# method calls that mutate their receiver: `self._events.append(x)` is
# a WRITE to _events for guarded-set inference, same as subscript
# assignment — the exact mutation a racing reader tears
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "update", "add",
    "discard", "setdefault", "sort", "reverse",
}
# attributes holding these ctors are internally synchronized — calling
# set()/clear()/put() on an Event/Queue needs no external lock, so they
# never enter the guarded set
_THREADSAFE_CTORS = {"Event", "Queue", "SimpleQueue", "LifoQueue",
                     "PriorityQueue", "local", "Barrier"}


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lock_ctor(node) -> bool:
    return isinstance(node, ast.Call) and \
        _dotted(node.func).rsplit(".", 1)[-1] in _LOCK_CTORS


def _lock_name_like(name: str) -> bool:
    low = name.lower()
    return "lock" in low or low.endswith(("_cv", "_cond", "_mutex"))


class _Access:
    __slots__ = ("attr", "write", "locked", "line", "func")

    def __init__(self, attr, write, locked, line, func):
        self.attr = attr
        self.write = write
        self.locked = locked
        self.line = line
        self.func = func


def _self_attr(node):
    """'X' when node is `self.X`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _with_locks(stmt: ast.With, lock_names, owner="self"):
    """Lock attrs among this with-statement's context managers."""
    held = set()
    for item in stmt.items:
        expr = item.context_expr
        if owner == "self":
            attr = _self_attr(expr)
            if attr is not None and attr in lock_names:
                held.add(attr)
        else:
            if isinstance(expr, ast.Name) and expr.id in lock_names:
                held.add(expr.id)
    return held


def _scan_method(fn, lock_names, accesses, method_names):
    """Collect self.X accesses in one method with lock-held context."""

    def walk(node, locked):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            # a closure does not inherit the lock it was created under
            for child in node.body:
                walk(child, False)
            return
        if isinstance(node, ast.With):
            held = _with_locks(node, lock_names)
            for item in node.items:
                walk(item.context_expr, locked)
            for child in node.body:
                walk(child, locked or bool(held))
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.append(_Access(attr, write, locked,
                                        node.lineno, fn.name))
            for child in ast.iter_child_nodes(node):
                walk(child, locked)
            return
        if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            # self._map[k] = v mutates _map: record the write, then the
            # normal walk records the Load of the container
            attr = _self_attr(node.value)
            if attr is not None:
                accesses.append(_Access(attr, True, locked,
                                        node.lineno, fn.name))
        if isinstance(node, ast.Call):
            # self.method(...) is a call, not state access — skip the
            # func attribute but scan the arguments
            attr = _self_attr(node.func)
            if attr is not None and attr in method_names:
                for child in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    walk(child, locked)
                return
            # self._events.append(x): a mutating method on a container
            # attribute is a write to that attribute
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    accesses.append(_Access(attr, True, locked,
                                            node.lineno, fn.name))
        if isinstance(node, ast.AugAssign):
            # x += 1 parses the target as Store only; it is a read AND
            # a write — record both so `self.n += 1` outside the lock
            # is caught as the read-modify-write race it is
            attr = _self_attr(node.target)
            if attr is not None:
                accesses.append(_Access(attr, False, locked,
                                        node.lineno, fn.name))
        for child in ast.iter_child_nodes(node):
            walk(child, locked)

    for stmt in fn.body:
        walk(stmt, False)


def _analyze_class(cls: ast.ClassDef, path: str, out: list) -> None:
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    method_names = {m.name for m in methods}
    lock_names, threadsafe = set(), set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if _is_lock_ctor(node.value) or (
                        _lock_name_like(attr)
                        and isinstance(node.value, ast.Call)):
                    lock_names.add(attr)
                elif isinstance(node.value, ast.Call) and _dotted(
                        node.value.func).rsplit(".", 1)[-1] in \
                        _THREADSAFE_CTORS:
                    threadsafe.add(attr)
    if not lock_names:
        return
    accesses: list = []
    for m in methods:
        if m.name in _SKIP_METHODS:
            continue
        _scan_method(m, lock_names, accesses, method_names)
    guarded = {a.attr for a in accesses
               if a.write and a.locked and a.attr not in lock_names
               and a.attr not in threadsafe}
    for a in accesses:
        if a.attr not in guarded or a.locked or a.attr in lock_names:
            continue
        if a.write:
            out.append(Violation(
                path, a.line, "PT101",
                f"{cls.name}.{a.func} writes lock-guarded attribute "
                f"`{a.attr}` outside `with self.<lock>:`"))
        else:
            out.append(Violation(
                path, a.line, "PT102",
                f"{cls.name}.{a.func} reads lock-guarded attribute "
                f"`{a.attr}` outside `with self.<lock>:`"))


def _local_bindings(fn) -> set:
    """Names bound locally in `fn` (params, plain assignments, loop/
    with/except targets) MINUS its `global` declarations — a Name whose
    id is in this set refers to a local, not the module global."""
    declared = {name for node in ast.walk(fn)
                if isinstance(node, ast.Global)
                for name in node.names}
    bound = {a.arg for a in (
        list(fn.args.posonlyargs) + list(fn.args.args)
        + list(fn.args.kwonlyargs))}
    for a in (fn.args.vararg, fn.args.kwarg):
        if a is not None:
            bound.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            bound.add(node.name)
    return bound - declared


def _analyze_module_level(tree: ast.Module, path: str, out: list) -> None:
    """The `_lock = threading.Lock()` + module-global state idiom.

    Candidate globals are the module's top-level assigned names; a
    function's access counts whenever the name is not shadowed by a
    local binding — reads never need a `global` statement, so requiring
    one would make every lock-free read invisible (the exact race class
    this layer exists for)."""
    lock_names = set()
    module_vars = set()
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                lock_names.update(names)
            else:
                module_vars.update(names)
    module_vars -= lock_names
    if not lock_names or not module_vars:
        return
    functions = [n for n in tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    accesses: list = []
    for fn in functions:
        visible = module_vars - _local_bindings(fn)
        declared = {name for node in ast.walk(fn)
                    if isinstance(node, ast.Global)
                    for name in node.names}
        watched = visible | (declared & module_vars)
        if not watched:
            continue

        def walk(node, locked, fn=fn, watched=watched):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return
            if isinstance(node, ast.With):
                held = _with_locks(node, lock_names, owner="global")
                for child in node.body:
                    walk(child, locked or bool(held))
                return
            if isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)) and isinstance(
                    node.value, ast.Name) and node.value.id in watched:
                accesses.append(_Access(node.value.id, True, locked,
                                        node.lineno, fn.name))
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and isinstance(
                    node.func.value, ast.Name) and \
                    node.func.value.id in watched:
                accesses.append(_Access(node.func.value.id, True,
                                        locked, node.lineno, fn.name))
            if isinstance(node, ast.Name) and node.id in watched:
                accesses.append(_Access(
                    node.id, isinstance(node.ctx, (ast.Store, ast.Del)),
                    locked, node.lineno, fn.name))
            for child in ast.iter_child_nodes(node):
                walk(child, locked)

        for stmt in fn.body:
            walk(stmt, False)
    guarded = {a.attr for a in accesses if a.write and a.locked}
    for a in accesses:
        if a.attr not in guarded or a.locked:
            continue
        rule = "PT101" if a.write else "PT102"
        verb = "writes" if a.write else "reads"
        out.append(Violation(
            path, a.line, rule,
            f"{a.func} {verb} module-lock-guarded global `{a.attr}` "
            f"outside `with <lock>:`"))


def analyze_source(source: str, path: str,
                   tree: ast.Module | None = None) -> list:
    if tree is None:
        tree = ast.parse(source)
    out: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _analyze_class(node, path, out)
    _analyze_module_level(tree, path, out)
    out.sort(key=Violation.sort_key)
    return out


def analyze_file(path: str, rel: str | None = None) -> list:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return analyze_source(source, rel or path)
