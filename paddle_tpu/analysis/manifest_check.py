"""Manifest consistency audit (rule PT301): OPS_MANIFEST.json vs the
live module surface.

`tools/gen_op_manifest.py` stamps each op with `present` (resolvable in
a public paddle_tpu namespace) and `tensor_method` (available as
``Tensor.<name>``). Those claims rot silently: a refactor that drops an
export keeps the manifest green until the next full regeneration. This
audit re-derives both bits from the *imported* package and fails
`pt_lint --check` on drift, so the manifest stays machine-true between
regenerations.

Resolution reuses `tools/gen_op_manifest._resolve` — the exact namespace
list the generator used — so the audit can never disagree with the
generator about what "present" means.
"""
from __future__ import annotations

import json
import os
import sys

from .report import Violation

__all__ = ["audit_manifest", "RULE_IDS"]

RULE_IDS = ("PT301",)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _entry_line(manifest_text: str, name: str) -> int:
    """Line of the op's entry in the json (file:line reporting)."""
    needle = f'"name": "{name}"'
    for i, line in enumerate(manifest_text.splitlines(), start=1):
        if needle in line:
            return i
    return 0


def audit_manifest(manifest_path: str | None = None) -> list:
    path = manifest_path or os.path.join(_REPO, "OPS_MANIFEST.json")
    rel = os.path.relpath(path, _REPO).replace("\\", "/")
    with open(path) as f:
        text = f.read()
    manifest = json.loads(text)

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from gen_op_manifest import _resolve
    finally:
        sys.path.pop(0)
    import paddle_tpu as P

    out = []
    for entry in manifest.get("ops", []):
        name = entry.get("name")
        if not name:
            continue
        where = _resolve(name)
        present = where is not None
        if bool(entry.get("present")) != present:
            out.append(Violation(
                rel, _entry_line(text, name), "PT301",
                f"op `{name}` claims present={entry.get('present')} "
                f"but live resolution says {present} — regenerate "
                f"the manifest"))
        elif present and entry.get("where") and \
                entry.get("where") != where:
            out.append(Violation(
                rel, _entry_line(text, name), "PT301",
                f"op `{name}` claims where={entry.get('where')!r} but "
                f"resolves in {where!r} — regenerate the manifest"))
        tm = hasattr(P.Tensor, name)
        if bool(entry.get("tensor_method")) != tm:
            out.append(Violation(
                rel, _entry_line(text, name), "PT301",
                f"op `{name}` claims tensor_method="
                f"{entry.get('tensor_method')} but Tensor.{name} "
                f"{'exists' if tm else 'does not exist'} — regenerate "
                f"the manifest"))
    return out
