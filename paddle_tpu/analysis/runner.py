"""Repo walker: run analysis layers over the tree, apply suppressions,
diff against the committed baseline.

The fast path (`layers=("ast", "lock")`) is pure stdlib — no jax, no
paddle_tpu import — so the tier-1 repo gate costs file IO plus ast
parses (~1 s for this tree). The `manifest`, `jaxpr` and `perf` layers
import the live package and are opt-in.

Determinism contract (tested): two runs over the same tree produce
byte-identical reports — files walked in sorted order, violations
sorted by (file, line, rule, message), no timestamps in the report.
"""
from __future__ import annotations

import ast
import os

from . import lock_check, trace_safety
from .report import Suppressions, Violation, render_report

__all__ = ["analyze_repo", "iter_python_files", "DEFAULT_ROOTS",
           "analyze_one_file"]

DEFAULT_ROOTS = ("paddle_tpu", "tools", "tests", "bench.py")
_SKIP_DIRS = {"__pycache__", "_build", ".git", ".jax_cache",
              "node_modules"}


def iter_python_files(repo_root: str, roots=DEFAULT_ROOTS):
    """Sorted repo-relative posix paths of the .py files to analyze."""
    found = []
    for root in roots:
        abs_root = os.path.join(repo_root, root)
        if os.path.isfile(abs_root):
            if abs_root.endswith(".py"):
                found.append(root.replace("\\", "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    found.append(rel.replace("\\", "/"))
    return sorted(set(found))


def analyze_one_file(abs_path: str, rel_path: str,
                     layers=("ast", "lock")) -> list:
    """Analyze one file; suppressions applied. A file that fails to
    parse yields a single PT000 finding instead of crashing the run."""
    with open(abs_path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(rel_path, e.lineno or 0, "PT000",
                          f"file does not parse: {e.msg}")]
    out = []
    # one parse shared by every layer (and the suppression index):
    # parsing dominates the fast path's cost
    sup = Suppressions(source, tree)
    if "ast" in layers:
        out.extend(trace_safety.analyze_source(source, rel_path,
                                               tree=tree))
    if "lock" in layers:
        # the suppression index doubles as the guard-claim source: a
        # def-line ok[PT101] "caller holds the lock" annotation feeds
        # the presumed-lock inference, not just post-hoc filtering
        out.extend(lock_check.analyze_source(source, rel_path,
                                             tree=tree,
                                             suppressions=sup))
    return sup.apply(out)


def analyze_repo(repo_root: str, roots=DEFAULT_ROOTS,
                 layers=("ast", "lock")) -> list:
    """All (unsuppressed) violations for the source layers, sorted."""
    out = []
    for rel in iter_python_files(repo_root, roots):
        out.extend(analyze_one_file(os.path.join(repo_root, rel), rel,
                                    layers))
    if "conc" in layers:
        # Layer 5 is whole-program (lock-order cycles cross files), so
        # it runs once over the tree, not per file; it applies
        # suppressions internally and scopes itself to the serving/
        # tooling roots (tests spin up racing threads on purpose)
        from .concurrency_audit import analyze_project

        out.extend(analyze_project(repo_root))
    if "manifest" in layers:
        from .manifest_check import audit_manifest

        out.extend(audit_manifest(
            os.path.join(repo_root, "OPS_MANIFEST.json")))
    if "jaxpr" in layers:
        from .hlo_audit import audit_op_table, audit_train_step

        out.extend(audit_op_table())
        out.extend(audit_train_step())
    if "perf" in layers:
        # findings only — the quantified metrics gate through
        # tools/perf_budget.json, not the violation baseline; use
        # perf_audit.audit_perf directly when the budget dict is needed
        from .perf_audit import audit_perf

        perf_v, _metrics = audit_perf(repo_root=repo_root)
        out.extend(perf_v)
    out.sort(key=Violation.sort_key)
    return out


def report(repo_root: str, **kwargs) -> str:
    return render_report(analyze_repo(repo_root, **kwargs))
